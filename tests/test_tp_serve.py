"""Tensor-parallel serving tests (serve/engine.py tp mesh,
parallel/sharding.py serve rules, analysis/spmd/manifest.py
serve_tp_manifest): bit-identity of tp=2 streams against tp=1 and one-shot
generate() — greedy, fixed-seed sampled, speculative, chunked prefill —
head-divisibility rejection, paged-pool sharding arithmetic (page axis
whole, head axis split, allocator unchanged), sharded hot-swap with zero
retraces under strict guards, the per-layer all-reduce comm manifest on
the hot program, and the deviation path when weights are deliberately
replicated. Runs on the suite's 8 virtual CPU devices; tier-1 except the
perf-marked BENCH_tp gate.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.analysis.spmd.hlo import (
    extract_collectives,
    summarize_collectives,
)
from pytorch_distributed_training_tpu.analysis.spmd.manifest import (
    serve_tp_manifest,
)
from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = [pytest.mark.serve, pytest.mark.tp]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# gpt2-tiny: 2 layers, hidden 64, 4 heads (tp=2 -> 2 heads per shard)
LAYERS, HIDDEN, HEADS = 2, 64, 4


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


def _want(model, params, prompts, T):
    return [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]


def _run_server(model, params, prompts, T, *, tp=1, temperature=0.0,
                top_k=0, seed=0, guards=None, registry=None, **cfg_kw):
    reg, sink = (registry, None) if registry is not None else _registry()
    cfg_kw.setdefault("prompt_buckets", (4, 8, 16))
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, max_new_tokens=T, kv_layout="paged",
            sampling="device", page_size=4, tp=tp, **cfg_kw,
        ),
        queue_depth=16, registry=reg, guards=guards,
    ).start()
    try:
        reqs = [
            server.submit(
                p, max_new_tokens=T, temperature=temperature, top_k=top_k,
                seed=seed + i,
            )
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs), [r.status for r in reqs]
    toks = [np.asarray(r.tokens, np.int32) for r in reqs]
    return toks, server.stats(), sink


# ------------------------------------------------------- stream identity


def test_tp_greedy_bit_identical_to_tp1_and_generate(lm):
    """The acceptance pin: a tp=2 engine's greedy streams are bit-identical
    to the single-device engine's AND to one-shot generate() — tensor
    parallelism is a partitioning knob, not a numerics change."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = _want(model, params, prompts, T)
    tp1, stats1, _ = _run_server(model, params, prompts, T, tp=1)
    tp2, stats2, _ = _run_server(model, params, prompts, T, tp=2)
    for i, (a, b, ref) in enumerate(zip(tp1, tp2, want)):
        np.testing.assert_array_equal(a, ref, err_msg=f"request {i} (tp1)")
        np.testing.assert_array_equal(b, ref, err_msg=f"request {i} (tp2)")
    assert stats1["tp"] == 1 and stats2["tp"] == 2


def test_tp_fixed_seed_sampled_identical(lm):
    """Fixed-seed sampled decode survives sharding exactly: the logits the
    sampler folds in are the SAME f32 values after the per-layer
    all-reduces, so (seed, step) streams match token for token."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 7, 12], seed=3)
    kw = dict(temperature=0.8, top_k=5, seed=11)
    tp1, _, _ = _run_server(model, params, prompts, T, tp=1, **kw)
    tp2, _, _ = _run_server(model, params, prompts, T, tp=2, **kw)
    for i, (a, b) in enumerate(zip(tp1, tp2)):
        assert len(b) == T
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")


def test_tp_spec_and_chunked_identical(lm):
    """Speculation and chunked prefill compose with sharding: the verify
    and chunk programs run under the same mesh and stay greedy-exact
    against the unsharded reference."""
    model, params = lm
    T = 5
    prompts = _prompts(model, [3, 9, 14, 16, 5], seed=2)
    want = _want(model, params, prompts, T)
    spec2, stats_s, _ = _run_server(
        model, params, prompts, T, tp=2, spec_k=3,
    )
    chunk2, stats_c, _ = _run_server(
        model, params, prompts, T, tp=2, prefill_chunk=4,
    )
    for i, (s, c, ref) in enumerate(zip(spec2, chunk2, want)):
        np.testing.assert_array_equal(s, ref, err_msg=f"request {i} (spec)")
        np.testing.assert_array_equal(
            c, ref, err_msg=f"request {i} (chunked)"
        )
    assert stats_s["spec_dispatches"] > 0
    assert stats_c["prefill_chunks"] > 0


# ------------------------------------------------------------ validation


def test_tp_head_divisibility_rejected(lm):
    """tp must divide num_heads and intermediate_size; the error names the
    offending axis and sizes instead of failing deep inside GSPMD."""
    model, params = lm
    with pytest.raises(ValueError, match=r"tp=3 does not divide.*num_heads=4"):
        InferenceServer(
            model, params,
            EngineConfig(
                num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
                kv_layout="paged", sampling="device", tp=3,
            ),
        )


def test_tp_requires_paged_device_sampling():
    with pytest.raises(ValueError, match="kv_layout"):
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
            kv_layout="dense", sampling="host", tp=2,
        )


# -------------------------------------------------- pool sharding layout


def test_tp_pool_sharding_arithmetic(lm):
    """The paged pools shard ONLY on the head axis: the page axis stays
    whole (allocator arithmetic and block tables are tp-invariant), each
    shard holds heads/tp heads, and pool capacity matches the tp=1
    engine's exactly."""
    from pytorch_distributed_training_tpu.parallel.sharding import (
        serve_pool_pspec,
    )

    model, params = lm

    def engine(tp):
        return InferenceServer(
            model, params,
            EngineConfig(
                num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
                kv_layout="paged", sampling="device", page_size=4, tp=tp,
            ),
        ).engine

    e1, e2 = engine(1), engine(2)
    pool_leaves = [
        leaf for leaf in jax.tree.leaves(e2._cache) if leaf.ndim == 4
    ]
    assert pool_leaves
    want_spec = serve_pool_pspec()
    for leaf in pool_leaves:
        assert leaf.sharding.spec == want_spec
        num_pages, page_size, heads, _head_dim = leaf.shape
        shard = leaf.sharding.shard_shape(leaf.shape)
        # page/page-size/head_dim axes whole, head axis split
        assert shard[0] == num_pages and shard[1] == page_size
        assert shard[2] == heads // 2 == HEADS // 2
    # allocator arithmetic is untouched by sharding: identical capacity
    s1, s2 = e1.stats(), e2.stats()
    assert s1["kv_pages_total"] == s2["kv_pages_total"]
    assert s1["kv_page_size"] == s2["kv_page_size"]


# ------------------------------- strict scope, comm manifest, hot swap


def test_tp_strict_scope_comm_manifest_and_sharded_swap_no_retrace(lm):
    """One strict-guard session covers the tick-wide contracts: the hot
    decode program's comm audit CONFORMS to serve_tp_manifest (exactly
    2 all-reduces per layer — attention-out + mlp_down — bounded bytes,
    no weight all-gather), cache donation survives sharded lowering, and a
    live hot swap lands as per-shard device_puts: zero retraces, zero
    implicit transfers, post-swap streams identical to serving the new
    weights from scratch."""
    from pytorch_distributed_training_tpu.analysis.guards import GuardSet

    model, pA = lm
    pB = jax.tree.map(lambda x: x + 0.01 * jnp.sign(x + 0.5), pA)
    reg, sink = _registry()
    gs = GuardSet(mode="strict", registry=reg)
    server = InferenceServer(
        model, pA,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8), max_new_tokens=4,
            kv_layout="paged", sampling="device", page_size=4,
            warmup=True, tp=2,
        ),
        queue_depth=16, registry=reg, guards=gs, weights_step=1,
    ).start()
    try:
        prompts = _prompts(model, [3, 6, 2, 7], seed=4)
        reqs = [
            server.submit(p, max_new_tokens=4, seed=i)
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
        ticket = server.engine.request_swap(pB, 2)
        assert ticket.done.wait(30) and ticket.ok
        prompt = _prompts(model, [5], seed=9)[0]
        r_post = server.submit(prompt, max_new_tokens=4)
        assert wait_until(r_post.done.is_set, timeout=120)
    finally:
        server.close()

    # swapped weights answer, bit-identical to a fresh unsharded serve
    want = np.asarray(
        generate(model, pB, prompt[None], max_new_tokens=4)
    )[0, len(prompt):]
    np.testing.assert_array_equal(np.asarray(r_post.tokens), want)

    stats = server.stats()
    assert stats["tp"] == 2 and stats["weights_step"] == 2
    assert stats["swaps"] == 1 and stats["swap_rollbacks"] == 0
    # the swap reused the load-time shardings: same placement, same
    # shapes -> the sharded programs never retraced
    assert stats["guard_recompiles"] == 0
    assert stats["guard_implicit_transfers"] == 0
    assert not sink.of("recompile") and not sink.of("implicit_transfer")

    (comm,) = sink.of("comm_audit")
    assert comm["name"] == "serve_decode" and comm["ok"] is True
    assert comm["deviations"] == []
    ar = comm["by_kind"]["all-reduce"]
    assert ar["count"] == 2 * LAYERS
    # payload per all-reduce: [slots=2, 1, hidden] f32 activations
    assert ar["bytes"] == 2 * LAYERS * (2 * 1 * HIDDEN * 4)
    assert "all-gather" not in comm["by_kind"]
    donations = [
        r for r in sink.of("donation_audit") if r["name"] == "serve_decode"
    ]
    assert donations and all(r.get("aliased") for r in donations)


def test_tp_manifest_catches_replicated_weights(lm):
    """The deviation path: compile the same model with every weight
    REPLICATED over the mesh — GSPMD then inserts no collectives at all —
    and the serve manifest must flag the missing required all-reduce."""
    from pytorch_distributed_training_tpu.comms.mesh import (
        MeshConfig,
        build_mesh,
    )

    model, params = lm
    mesh = build_mesh(
        MeshConfig(data=1, fsdp=1, stage=1, model=2, seq=1),
        devices=jax.devices()[:2],
    )
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    params_r = jax.device_put(params, jax.tree.map(lambda _: repl, params))
    tokens = jax.device_put(jnp.ones((2, 4), jnp.int32), repl)
    txt = (
        jax.jit(lambda p, t: model.apply({"params": p}, t))
        .lower(params_r, tokens)
        .compile()
        .as_text()
    )
    summary = summarize_collectives(extract_collectives(txt, world_size=2))
    manifest = serve_tp_manifest(
        2, layers=LAYERS, hidden=HIDDEN, max_q_tokens=2,
    )
    deviations = manifest.check(summary)
    assert any(
        "required" in d and "all-reduce" in d for d in deviations
    ), deviations


def test_tp_manifest_moved_bytes_ceiling():
    """The ring-cost ceiling trips on an oversized footprint even when the
    kind set is legal."""
    manifest = serve_tp_manifest(2, layers=LAYERS, hidden=HIDDEN,
                                 max_q_tokens=2)
    assert manifest.required == ("all-reduce",)
    big = {
        "count": 4,
        "by_kind": {"all-reduce": {"count": 4}},
        "total_bytes": manifest.max_bytes,
        "total_moved_bytes": manifest.max_moved_bytes + 1,
    }
    deviations = manifest.check(big)
    assert any("moved-bytes ceiling" in d for d in deviations), deviations


# ------------------------------------------------------------ perf gate


@pytest.mark.perf
def test_tp_bench_gate(tmp_path):
    """bench.py --tp: tp=2 must emit BIT-IDENTICAL token streams to tp=1
    (with and without speculation), sustain throughput, and its hot
    programs' compile-time comm audits must conform to serve_tp_manifest
    with the exact per-tick collective footprint — the PR's acceptance
    gate."""
    out = tmp_path / "BENCH_tp.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--tp", "--tp-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    assert result["streams_identical"] is True, result["stream_digests"]
    assert result["comm_audit_ok"] is True
    slots = 4
    for name, q in (("tp2", 1), ("tp2_spec", 7 + 1)):
        v = result[name]
        assert v["tp"] == 2 and v["tokens_per_s"] > 0
        assert v["page_exhausted"] == 0
        audits = {a["name"]: a for a in v["comm_audits"]}
        hot = "serve_verify" if q > 1 else "serve_decode"
        a = audits[hot]
        assert a["ok"] is True and a["deviations"] == []
        ar = a["by_kind"]["all-reduce"]
        assert ar["count"] == 2 * LAYERS
        # per-tick payload: 2 ARs/layer x [slots, q, hidden] f32
        assert a["total_bytes"] == 2 * LAYERS * (slots * q * HIDDEN * 4)
        assert "all-gather" not in a["by_kind"]
    for name in ("tp1", "tp1_spec"):
        assert result[name]["tp"] == 1
        assert result[name]["tokens_per_s"] > 0
