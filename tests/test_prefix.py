"""Shared-KV prefix cache tests (serve/prefix_cache.py, the refcount half
of serve/paged_cache.py and their engine integration): page refcount
lifecycle + misuse guards (double release, writing/freeing shared pages),
trie match/insert/evict edges, copy-on-write at a mid-page divergence,
the acceptance pin that cached-prefix streams are BIT-IDENTICAL to cold
prefill (greedy and fixed-seed sampled; plain, chunked and speculative
engines; tp=2 and weight-int8 variants), tenant-quota fairness, eviction
under page pressure never corrupting an in-flight stream, hot-swap
invalidation (post-swap streams never reuse pre-swap pages), the
multi-tenant trace mix determinism pin, and the telemetry surface
(gauges, admission-span attrs, /healthz page split). CPU, tier-1 except
the perf-marked BENCH_prefix gate.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.paged_cache import PageAllocator
from pytorch_distributed_training_tpu.serve.prefix_cache import PrefixCache
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = [pytest.mark.serve, pytest.mark.prefix]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _shared_prompts(model, prefix_len, tail_lens, seed=0):
    """Prompts sharing one ``prefix_len``-token system prefix with random
    private tails — the workload the cache exists for."""
    rng = np.random.default_rng(seed)
    vocab = model.config.vocab_size
    prefix = rng.integers(1, vocab, prefix_len).astype(np.int32)
    return [
        np.concatenate([prefix, rng.integers(1, vocab, n).astype(np.int32)])
        for n in tail_lens
    ]


def _want(model, params, prompts, T):
    return [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]


def _serve_serial(server, prompts, T, **submit_kw):
    """Submit one at a time so the first request INSERTS before the rest
    match — deterministic hit pattern regardless of tick interleaving."""
    toks = []
    for i, p in enumerate(prompts):
        r = server.submit(p, max_new_tokens=T, **submit_kw)
        assert wait_until(r.done.is_set, timeout=120), r.status
        assert r.status == "done", r.status
        toks.append(np.asarray(r.tokens, np.int32))
    return toks


# ------------------------------------------------------ allocator refcounts


def test_refcount_acquire_share_release():
    alloc = PageAllocator(
        num_pages=9, page_size=4, pages_per_slot=4, num_slots=3
    )
    alloc.admit(0, 2)
    a, b = alloc.slot_pages(0)
    assert alloc.refcount(a) == 1 and alloc.pages_shared == 0

    # share page a into slot 1's row alongside a private page
    alloc.admit_shared(1, [a], 1)
    assert alloc.refcount(a) == 2 and alloc.pages_shared == 1
    assert alloc.block_table[1][0] == a
    # shared pages are not double-counted as used
    assert alloc.pages_used == 3

    # releasing the original holder must NOT free the shared page
    alloc.release(0)
    assert alloc.refcount(a) == 1 and alloc.refcount(b) == 0
    assert a not in alloc._free
    # the last holder's release finally frees it
    alloc.release(1)
    assert alloc.refcount(a) == 0 and alloc.pages_used == 0
    assert alloc.pages_free == 8


def test_refcount_misuse_guards():
    alloc = PageAllocator(
        num_pages=6, page_size=4, pages_per_slot=8, num_slots=2
    )
    alloc.admit(0, 2)
    a, b = alloc.slot_pages(0)

    # acquire only shares LIVE pages; out-of-range and the null page raise
    free_page = next(
        p for p in range(1, 6) if alloc.refcount(p) == 0
    )
    with pytest.raises(RuntimeError, match="free"):
        alloc.acquire(free_page)
    with pytest.raises(ValueError, match="out of range"):
        alloc.acquire(0)
    with pytest.raises(ValueError, match="out of range"):
        alloc.acquire(6)

    # double release of an already-free page raises (a freed id may be in
    # another slot's row — silence would corrupt it)
    alloc.release(0)
    with pytest.raises(RuntimeError, match="double release"):
        alloc.decref(a)

    # admit_shared misuse mirrors admit's guards
    alloc.admit(0, 1)
    (p,) = alloc.slot_pages(0)
    with pytest.raises(RuntimeError, match="already holds"):
        alloc.admit_shared(0, [p], 1)
    with pytest.raises(ValueError, match="block-table rows"):
        alloc.admit_shared(1, [p], 8)
    with pytest.raises(RuntimeError, match="exhausted"):
        alloc.admit_shared(1, [p], alloc.pages_free + 1)
    # failed admits leak nothing
    assert alloc.refcount(p) == 1 and alloc.slot_pages(1) == ()


def test_cow_repoints_private_copy_and_guards():
    alloc = PageAllocator(
        num_pages=6, page_size=4, pages_per_slot=8, num_slots=2
    )
    alloc.admit(0, 1)
    (shared,) = alloc.slot_pages(0)

    # writing an exclusively-held page needs no cow — calling it is a bug
    with pytest.raises(RuntimeError, match="exclusively-held"):
        alloc.cow(0, 0)

    alloc.admit_shared(1, [shared], 1)
    old, new = alloc.cow(1, 0)
    assert old == shared and new != shared
    assert alloc.block_table[1][0] == new
    assert alloc.slot_pages(1)[0] == new
    # the old page kept its other holder; the copy is private
    assert alloc.refcount(shared) == 1 and alloc.refcount(new) == 1
    assert alloc.pages_shared == 0

    # cow with a drained free list raises rather than corrupting
    alloc.release(1)
    alloc.admit_shared(1, [shared], alloc.pages_free)
    with pytest.raises(RuntimeError, match="exhausted"):
        alloc.cow(1, 0)


def test_release_order_lifo_free_list_preserved_under_sharing():
    """The refcount layer must not perturb the LIFO reuse order pinned by
    test_paged.py: a release returns a slot's pages such that a same-size
    re-admit gets the same pages back in the same order."""
    alloc = PageAllocator(
        num_pages=9, page_size=4, pages_per_slot=3, num_slots=2
    )
    alloc.admit(0, 3)
    first = alloc.slot_pages(0)
    alloc.release(0)
    alloc.admit(0, 3)
    assert alloc.slot_pages(0) == first


# ------------------------------------------------------------------- trie


def _trie(num_pages=16, page_size=4, num_slots=4):
    alloc = PageAllocator(
        num_pages=num_pages, page_size=page_size,
        pages_per_slot=num_pages, num_slots=num_slots,
    )
    return PrefixCache(alloc), alloc


def test_trie_insert_match_exact_and_partial():
    cache, alloc = _trie()
    toks = list(range(100, 110))            # 2 full pages + 2 leftover
    alloc.admit(0, 3)
    pages = alloc.slot_pages(0)

    # only FULL pages are indexed; the partial third page is not
    assert cache.insert(toks, pages) == 2
    assert cache.cached_pages == 2
    assert alloc.refcount(pages[0]) == 2 and alloc.refcount(pages[2]) == 1

    # exact full-page match, no cow source
    m = cache.match(toks[:8])
    assert m.hit and m.pages == pages[:2]
    assert m.cached_len == 8 and m.cow_src is None

    # mid-page divergence: 1 full page + 2 tokens into the second ->
    # the second page is the copy-on-write source
    m = cache.match(toks[:6] + [999, 998])
    assert m.pages == pages[:1] and m.cached_len == 6
    assert m.cow_src == pages[1]

    # divergence inside the FIRST page: no full pages, cow only
    m = cache.match(toks[:3] + [999])
    assert m.pages == () and m.cached_len == 3 and m.cow_src == pages[0]
    assert m.hit

    # total miss
    m = cache.match([1, 2, 3, 4, 5])
    assert not m.hit and m.pages == () and m.cow_src is None

    # note() is the only counter path — match alone never counts
    assert cache.hits == 0 and cache.misses == 0
    cache.note(True)
    cache.note(False)
    assert cache.stats()["prefix_hit_rate"] == 0.5


def test_trie_first_writer_wins_and_insert_guards():
    cache, alloc = _trie()
    toks = list(range(200, 208))
    alloc.admit(0, 2)
    alloc.admit(1, 2)
    p0, p1 = alloc.slot_pages(0), alloc.slot_pages(1)

    assert cache.insert(toks, p0) == 2
    # a duplicate insert from another slot creates nothing and bumps no
    # refcount — the first writer's pages stay canonical
    assert cache.insert(toks, p1) == 0
    assert cache.cached_pages == 2
    assert alloc.refcount(p1[0]) == 1

    # divergent second half: shares the first node, adds one
    toks2 = toks[:4] + [777, 778, 779, 780]
    alloc.admit(2, 2)
    p2 = alloc.slot_pages(2)
    assert cache.insert(toks2, p2) == 1
    assert cache.cached_pages == 3
    # the shared first page was NOT re-acquired (node already existed)
    assert alloc.refcount(p0[0]) == 2

    with pytest.raises(ValueError, match="full pages"):
        cache.insert(list(range(12)), p0[:2])


def test_trie_evict_lru_protect_and_idle():
    cache, alloc = _trie()
    runs = []
    for slot, base in enumerate((100, 200, 300)):
        toks = [base + i for i in range(8)]
        alloc.admit(slot, 2)
        cache.insert(toks, alloc.slot_pages(slot))
        runs.append((toks, alloc.slot_pages(slot)))
        alloc.release(slot)               # cache-only now (refcount 1)

    # freshen run 0 so run 1 is the LRU victim
    cache.match(runs[0][0])
    freed_before = alloc.pages_free
    assert cache.evict_until(1) == 1
    assert alloc.pages_free == freed_before + 1
    # leaf-first: the run's SECOND page went first
    assert alloc.refcount(runs[1][1][1]) == 0
    assert alloc.refcount(runs[1][1][0]) == 1

    # protect pins pages an in-progress match is about to map
    protected = set(runs[0][1])
    assert cache.evict_until(100, protect=protected) >= 1
    for page in protected:
        assert alloc.refcount(page) == 1    # survived a drain-everything

    # pages still referenced by a slot are never evictable
    alloc.admit_shared(3, list(runs[0][1]), 0)
    assert cache.evict_until(100) == 0
    assert cache.cached_pages == 2

    # evict_idle drops every cache-only run; slot-shared entries survive
    alloc.release(3)
    assert cache.evict_idle() == 2
    assert cache.cached_pages == 0
    assert alloc.pages_used == 0


def test_trie_invalidate_all_keeps_inflight_pages_alive():
    cache, alloc = _trie()
    toks = list(range(50, 58))
    alloc.admit(0, 2)
    cache.insert(toks, alloc.slot_pages(0))
    shared = alloc.slot_pages(0)

    # slot 1 shares the cached run (an in-flight hit) when the flush lands
    alloc.admit_shared(1, list(shared), 0)
    dropped = cache.invalidate_all()
    assert dropped == 2 and cache.cached_pages == 0
    assert cache.stats()["prefix_invalidations"] == 1

    # the in-flight slots keep their pages; nothing was freed under them
    assert alloc.refcount(shared[0]) == 2
    assert not cache.match(toks[:8]).hit
    alloc.release(0)
    alloc.release(1)
    assert alloc.pages_used == 0


# -------------------------------------------------- engine: cached == cold


def _run_prefix_server(model, params, prompts, T, *, registry=None,
                       submit_kw=None, **cfg_kw):
    reg, sink = (registry, None) if registry is not None else _registry()
    cfg_kw.setdefault("prompt_buckets", (24,))
    cfg_kw.setdefault("page_size", 4)
    cfg_kw.setdefault("num_pages", 64)
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, max_new_tokens=T, kv_layout="paged",
            sampling="device", prefix_cache=True, **cfg_kw,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        toks = _serve_serial(server, prompts, T, **(submit_kw or {}))
    finally:
        server.close()
    return toks, server.stats(), sink, server


@pytest.mark.parametrize("variant", ["plain", "chunked", "spec"])
def test_cached_greedy_bit_identical_to_cold_with_cow(lm, variant):
    """THE acceptance pin: streams served from cached prefixes are
    token-identical to one-shot generate() — with the shared prefix
    deliberately NOT page-aligned (14 tokens, page_size 4) so every hit
    exercises the copy-on-write path — across the plain, chunked-prefill
    and speculative engines."""
    model, params = lm
    T = 5
    cfg_kw = {
        "plain": {},
        "chunked": dict(prefill_chunk=4),
        "spec": dict(spec_k=2, spec_draft="ngram"),
    }[variant]
    prompts = _shared_prompts(model, 14, [4, 6, 3], seed=3)
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_prefix_server(
        model, params, prompts, T, **cfg_kw
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"{variant} req {i}")
    pc = stats["prefix_cache"]
    assert pc["prefix_hits"] == 2 and pc["prefix_lookups"] == 3
    assert pc["cow_copies"] == 2          # 14 % 4 != 0: every hit COWs
    assert stats["page_exhausted"] == 0
    # each hit skipped at least the 12 fully-paged shared tokens
    cold = sum(len(p) for p in prompts)
    assert stats["prefill_tokens"] <= cold - 2 * 12


def test_cached_sampled_fixed_seed_identical_to_cache_off(lm):
    """Fixed-seed sampled decode is exact across the cache: the same
    submissions through a prefix_cache engine and a cache-off engine yield
    identical tokens (device sampling keys on (seed, position) only)."""
    model, params = lm
    T = 6
    prompts = _shared_prompts(model, 12, [5, 7, 4], seed=11)
    kw = dict(temperature=0.8, top_k=5, seed=9)
    cached, stats, _, _ = _run_prefix_server(
        model, params, prompts, T, submit_kw=kw
    )
    assert stats["prefix_cache"]["prefix_hits"] == 2

    reg, _ = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(24,), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4, num_pages=64,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        cold = _serve_serial(server, prompts, T, **kw)
    finally:
        server.close()
    for i, (a, b) in enumerate(zip(cached, cold)):
        assert len(a) == T
        np.testing.assert_array_equal(a, b, err_msg=f"request {i}")


@pytest.mark.tp
def test_cached_tp2_bit_identical_to_generate(lm):
    """tp=2: the head-sharded engine's cache-hit streams (COW copies over
    page-leading sharded pools included) stay greedy-exact."""
    model, params = lm
    T = 5
    prompts = _shared_prompts(model, 14, [4, 6], seed=5)
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_prefix_server(
        model, params, prompts, T, tp=2
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"tp2 req {i}")
    pc = stats["prefix_cache"]
    assert pc["prefix_hits"] == 1 and pc["cow_copies"] == 1


def test_cached_int8_weights_bit_identical_on_snapped_grid(lm):
    """Weight-only int8 + prefix cache: on int8-grid weights the cached
    streams match the fp32 reference bit-for-bit (the COW copy must also
    cover the int8 engine's pool tree)."""
    from pytorch_distributed_training_tpu.ops.quant import (
        dequantize_serve_params,
        quantize_serve_params,
    )

    model, params = lm
    snapped = dequantize_serve_params(quantize_serve_params(params))
    T = 5
    prompts = _shared_prompts(model, 14, [4, 5], seed=13)
    want = _want(model, snapped, prompts, T)
    toks, stats, _, _ = _run_prefix_server(
        model, snapped, prompts, T, weights_dtype="int8"
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"int8 req {i}")
    assert stats["variant"] == "int8"
    assert stats["prefix_cache"]["prefix_hits"] == 1


# ------------------------------------------------------------ tenant lanes


def test_queue_tenant_lanes_blocked_tenant_does_not_freeze_others():
    from pytorch_distributed_training_tpu.serve.queue import (
        GenRequest,
        RequestQueue,
    )

    q = RequestQueue(max_depth=8, prompt_buckets=(8,), max_new_tokens=4)

    def sub(rid, tenant):
        return q.submit(GenRequest(
            id=rid, prompt_ids=np.ones(3, np.int32), max_new_tokens=4,
            tenant=tenant,
        ))

    a1, a2 = sub("a1", "ta"), sub("a2", "ta")
    b1 = sub("b1", "tb")

    # tenant ta's head is rejected: its OWN later request may not bypass
    # it, but tenant tb's head (submitted after both) still pops
    popped = q.pop_ready(accept=lambda r: r.tenant != "ta")
    assert popped is b1
    assert q.depth() == 2

    # once ta unblocks, its requests drain in submit order
    assert q.pop_ready() is a1
    assert q.pop_ready() is a2
    assert q.pop_ready() is None

    # tenantless traffic keeps the historical strict-FIFO no-bypass rule
    c1, c2 = sub("c1", None), sub("c2", None)
    assert q.pop_ready(accept=lambda r: r is not c1) is None
    assert q.pop_ready() is c1 and q.pop_ready() is c2

    with pytest.raises(ValueError, match="tenant"):
        q.submit(GenRequest(
            id="bad", prompt_ids=np.ones(3, np.int32), max_new_tokens=4,
            tenant="",
        ))


def test_tenant_quota_holds_flood_without_page_exhaustion(lm):
    """A tenant over its private-page quota is HELD (tenant_blocked ticks
    up, page_exhausted does not) while other tenants keep being served;
    the flood drains once its own slots release pages."""
    model, params = lm
    T = 4
    prompts = _shared_prompts(model, 8, [4, 5, 3, 6, 4], seed=17)
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(16,), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4,
            num_pages=33,                   # 32 usable
            prefix_cache=True, tenant_page_quota=0.1875,  # 6 pages/tenant
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        # flood tenant: the cold head reserves ceil((16+4)/4)=5 private
        # pages and a hit still needs 3 fresh tail pages, so two ta
        # requests in flight (>= 8) breach the 6-page quota — the quota
        # serializes them while tb rides alongside
        reqs = [
            server.submit(p, max_new_tokens=T,
                          tenant="ta" if i != 2 else "tb")
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs)
    want = _want(model, params, prompts, T)
    for i, (req, ref) in enumerate(zip(reqs, want)):
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref, err_msg=f"request {i}"
        )
    stats = server.stats()
    assert stats["prefix_cache"]["tenant_blocked"] > 0
    assert stats["page_exhausted"] == 0     # quota holds are not exhaustion
    assert stats["prefix_cache"]["tenant_page_quota"] == 0.1875


# ------------------------------------------- eviction + swap invalidation


def test_eviction_under_pressure_never_corrupts_streams(lm):
    """A pool too small to hold the cache AND fresh admissions LRU-evicts
    idle cached runs instead of blocking: a cold foreign-prefix request
    forces eviction of the resident runs, later same-prefix hits force
    eviction WHILE their own matched pages must be protected — and every
    stream stays greedy-exact with zero page_exhausted."""
    model, params = lm
    T = 4
    # 8 usable pages; every request reserves ceil((16+4)/4) = 5, a
    # finished prompt leaves 2-3 cached pages behind -> from the third
    # admission on, free pages only exist by evicting cached runs
    shared_a = _shared_prompts(model, 8, [4, 5, 6, 3], seed=23)
    foreign = _shared_prompts(model, 8, [4], seed=24)
    prompts = shared_a[:2] + foreign + shared_a[2:]
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_prefix_server(
        model, params, prompts, T,
        prompt_buckets=(16,), num_pages=9,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
    pc = stats["prefix_cache"]
    assert pc["prefix_evictions"] > 0
    assert pc["prefix_hits"] >= 2           # eviction didn't kill sharing
    assert stats["page_exhausted"] == 0
    assert stats["kv_pages_used"] == pc["prefix_cached_pages"]


def test_hotswap_invalidates_prefix_index(lm):
    """Cached KV is a function of the weights that wrote it: a hot-swap
    flushes the whole index, so a post-swap repeat of a pre-swap prompt is
    a MISS served entirely by the new weights (and never maps a pre-swap
    page)."""
    model, params = lm
    pB = jax.tree.map(lambda x: x + 0.01 * jnp.sign(x), params)
    T = 5
    prompts = _shared_prompts(model, 12, [4, 6], seed=29)
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(24,), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4, num_pages=64,
            prefix_cache=True,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        pre = _serve_serial(server, prompts, T)
        np.testing.assert_array_equal(pre[0], _want(model, params, prompts, T)[0])
        assert server.stats()["prefix_cache"]["prefix_hits"] == 1

        ticket = server.engine.request_swap(pB, 2)
        assert ticket.done.wait(60) and ticket.ok

        # the same prompts again: pure misses on a flushed index, streams
        # token-identical to the NEW weights' cold answers
        post = _serve_serial(server, prompts, T)
        stats = server.stats()
    finally:
        server.close()
    want_b = _want(model, pB, prompts, T)
    for i, (got, ref) in enumerate(zip(post, want_b)):
        np.testing.assert_array_equal(got, ref, err_msg=f"post-swap req {i}")
    pc = stats["prefix_cache"]
    assert pc["prefix_invalidations"] == 1
    # post-swap: one fresh miss then one fresh hit (rebuilt from new-weight
    # pages) — the pre-swap entries contributed nothing
    assert pc["prefix_lookups"] == 4 and pc["prefix_hits"] == 2
    # the weights actually moved (guards against a vacuous identity)
    assert not np.array_equal(pre[0], post[0])


# ------------------------------------------------------ telemetry surface


def test_prefix_gauges_span_attrs_and_health_page_split(lm):
    model, params = lm
    T = 4
    prompts = _shared_prompts(model, 12, [4, 5], seed=31)
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(24,), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4, num_pages=64,
            prefix_cache=True,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        r0 = server.submit(prompts[0], max_new_tokens=T)
        assert wait_until(r0.done.is_set, timeout=120)
        health_mid = server.health()
        r1 = server.submit(prompts[1], max_new_tokens=T)
        assert wait_until(r1.done.is_set, timeout=120)
    finally:
        server.close()

    # gauges landed
    gauges = reg.snapshot()["gauges"]
    for name in ("serve/prefix_hit_rate", "serve/pages_shared",
                 "serve/cow_copies"):
        assert name in gauges, name
    assert gauges["serve/prefix_hit_rate"] == 0.5

    # the admission span carries the hit attribution
    from pytorch_distributed_training_tpu.telemetry.spans import (
        spans_by_trace,
    )

    traces = spans_by_trace(sink.records)
    adm0 = {s["name"]: s for s in traces[r0.id]}["admission"]
    adm1 = {s["name"]: s for s in traces[r1.id]}["admission"]
    assert adm0["attrs"]["prefix_hit"] is False
    assert adm0["attrs"]["cached_tokens"] == 0
    assert adm1["attrs"]["prefix_hit"] is True
    assert adm1["attrs"]["cached_tokens"] == 12

    # /healthz exposes the shared/free page split beside the load fields
    assert health_mid["kv_pages_shared"] == 0     # cached, not yet shared
    assert health_mid["kv_pages_free"] > 0
    st = server.stats()
    assert st["kv_pages_shared"] == 0             # both requests finished
    assert st["prefix_cache"]["pages_shared"] == 0


# ------------------------------------------------------- trace tenant mix


def test_trace_tenant_mix_deterministic_and_single_tenant_unchanged():
    from pytorch_distributed_training_tpu.serve.trace import (
        TraceConfig,
        generate_trace,
        trace_stats,
    )

    # the legacy pin, extended: tenants=0 must consume the IDENTICAL rng
    # stream as before the field existed — same config, same events, no
    # tenant fields set
    base = TraceConfig(seed=4, duration_s=6.0)
    a, b = generate_trace(base), generate_trace(base)
    assert a == b
    assert all(ev.tenant is None and ev.prefix_len == 0 for ev in a)

    mix = TraceConfig(
        seed=4, duration_s=6.0, tenants=3, shared_prefix_len=16,
    )
    m1, m2 = generate_trace(mix), generate_trace(mix)
    assert m1 == m2 and len(m1) > 0
    names = {ev.tenant for ev in m1}
    assert names <= {"tenant0", "tenant1", "tenant2"} and len(names) >= 2
    for ev in m1:
        assert ev.prefix_len == 16
        # shared prefix + at least one private token, still bounded
        assert ev.prompt_len >= 17
        assert ev.prompt_len <= max(mix.prompt_len_max, 17)
    st = trace_stats(m1)
    assert sum(st["by_tenant"].values()) == len(m1)

    with pytest.raises(ValueError, match="tenants"):
        TraceConfig(tenants=-1)
    with pytest.raises(ValueError, match="shared_prefix_len"):
        TraceConfig(tenants=2, shared_prefix_len=0)


# ------------------------------------------------------------ config guards


def test_prefix_cache_config_validation():
    with pytest.raises(ValueError, match="prefix_cache"):
        EngineConfig(kv_layout="dense", prefix_cache=True)
    with pytest.raises(ValueError, match="prefix_cache"):
        EngineConfig(
            kv_layout="paged", sampling="host", prefix_cache=True,
        )
    with pytest.raises(ValueError, match="tenant_page_quota"):
        EngineConfig(kv_layout="paged", tenant_page_quota=1.5)
    with pytest.raises(ValueError, match="tenant_page_quota"):
        EngineConfig(
            kv_layout="paged", sampling="device", tenant_page_quota=0.5,
        )


# --------------------------------------------------------------- perf gate


@pytest.mark.perf
def test_prefix_bench_cache_beats_cold(tmp_path):
    """bench.py --prefix: on the multi-tenant shared-prefix workload the
    cache must cut prefill tokens >= 30% and TTFT vs cold prefill with
    BIT-IDENTICAL stream digests, a real hit rate and zero page
    exhaustion (the PR's perf acceptance gate)."""
    out = tmp_path / "BENCH_prefix.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--prefix", "--prefix-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    cold, cached = result["cold"], result["cached"]
    assert result["streams_identical"] is True
    assert cold["stream_digest"] == cached["stream_digest"]
    assert result["prefill_token_reduction"] >= 0.30, result
    assert cached["ttft_s"]["p50"] <= cold["ttft_s"]["p50"], result
    assert cached["prefix"]["prefix_hit_rate"] > 0.5
    assert cold["page_exhausted"] == 0 and cached["page_exhausted"] == 0
