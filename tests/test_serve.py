"""Serving subsystem tests (serve/): slot lifecycle, scheduler policies,
decode parity with one-shot generate(), backpressure, deadlines, shutdown,
front-ends, telemetry and fault-injection integration. CPU, tier-1.
"""

import io
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.serve import (
    BackpressureError,
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = pytest.mark.serve


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


def test_slot_admit_evict_reuse_matches_one_shot(lm):
    """5 ragged requests through 2 slots: every slot is reused, every
    request's greedy continuation is IDENTICAL to a one-shot batch-1
    generate() of the same prompt at its exact length (no padding)."""
    model, params = lm
    reg, sink = _registry()
    lengths = [3, 5, 9, 14, 6]
    prompts = _prompts(model, lengths, seed=7)
    T = 5
    want = [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]

    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=2, prompt_buckets=(4, 8, 16), max_new_tokens=T),
        queue_depth=8, registry=reg,
    ).start()
    try:
        reqs = [server.submit(p, max_new_tokens=T) for p in prompts]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()

    for i, (req, ref) in enumerate(zip(reqs, want)):
        assert req.status == "done" and req.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref,
            err_msg=f"request {i} (len {lengths[i]})",
        )
    stats = server.stats()
    # 5 admissions through 2 slots = slots were evicted and reused
    assert stats["admitted"] == 5 and stats["num_slots"] == 2
    assert stats["finished"] == 5 and stats["queue_depth"] == 0
    assert stats["slot_occupancy"] == 0.0
    # one compiled prefill per bucket USED (bounded compilation), one record
    # per request in the telemetry stream
    assert stats["compiled_prefill_buckets"] == [4, 8, 16]
    recs = sink.of("serve_request")
    assert len(recs) == 5
    for r in recs:
        assert r["status"] == "done" and r["new_tokens"] == T
        assert r["ttft_s"] is not None and r["queue_wait_s"] is not None


def test_slotted_decode_bitwise_vs_one_shot_same_shapes(lm):
    """Acceptance pin: bucket == prompt length and cache_len == generate()'s
    total_len make the compiled programs shape-identical — greedy token ids
    must match one-shot generation exactly while 3 slots decode together."""
    model, params = lm
    L, T = 8, 6
    prompts = _prompts(model, [L, L, L], seed=0)
    want = [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[0, L:]
        for p in prompts
    ]
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=3, prompt_buckets=(L,), max_new_tokens=T),
        queue_depth=4,
    ).start()
    try:
        reqs = [server.submit(p, max_new_tokens=T) for p in prompts]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()
    for req, ref in zip(reqs, want):
        np.testing.assert_array_equal(np.asarray(req.tokens, np.int32), ref)


def test_eot_stops_decode(lm):
    """A request whose sampled token equals its eot_id finishes with reason
    'eot' instead of decoding to max_new_tokens."""
    model, params = lm
    prompts = _prompts(model, [5], seed=2)
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=6),
        queue_depth=2,
    ).start()
    try:
        probe = server.submit(prompts[0], max_new_tokens=6)
        assert wait_until(probe.done.is_set, timeout=120)
        eot = probe.tokens[0]  # greedy: the same first token will recur
        req = server.submit(prompts[0], max_new_tokens=6, eot_id=eot)
        assert wait_until(req.done.is_set, timeout=120)
    finally:
        server.close()
    assert req.finish_reason == "eot"
    assert req.tokens == [eot]


def test_sampling_deterministic_per_seed(lm):
    model, params = lm
    prompts = _prompts(model, [6], seed=3)
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=2, prompt_buckets=(8,), max_new_tokens=6),
        queue_depth=4,
    ).start()
    try:
        kw = dict(max_new_tokens=6, temperature=1.5)
        a = server.submit(prompts[0], seed=11, **kw)
        b = server.submit(prompts[0], seed=11, **kw)
        c = server.submit(prompts[0], seed=12, **kw)
        assert wait_until(
            lambda: all(r.done.is_set() for r in (a, b, c)), timeout=120
        )
    finally:
        server.close()
    assert a.tokens == b.tokens
    assert a.tokens != c.tokens
    assert all(0 <= t < model.config.vocab_size for t in a.tokens)


def test_backpressure_rejects_never_hangs(lm):
    """Submissions beyond queue capacity fail FAST with BackpressureError
    (the engine loop is deliberately not running, so nothing drains)."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=4),
        queue_depth=2,
    )
    prompts = _prompts(model, [4, 4, 4], seed=4)
    server.submit(prompts[0], max_new_tokens=2)
    server.submit(prompts[1], max_new_tokens=2)
    t0 = time.monotonic()
    with pytest.raises(BackpressureError):
        server.submit(prompts[2], max_new_tokens=2)
    assert time.monotonic() - t0 < 1.0  # rejected, not queued-and-hung
    # out-of-contract requests are rejected with ValueError, same O(1) path
    with pytest.raises(ValueError):
        server.submit(np.arange(1, 30, dtype=np.int32), max_new_tokens=2)
    with pytest.raises(ValueError):
        server.submit(prompts[0], max_new_tokens=99)
    server.close(drain=False)


def test_oversized_top_k_is_safe(lm):
    """A client top_k larger than the vocab must not crash the serve loop:
    it degrades to full-vocab sampling. Negative top_k is rejected O(1) at
    submit, same path as the other out-of-contract params."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=4),
        queue_depth=2,
    ).start()
    try:
        req = server.submit(
            _prompts(model, [4], seed=13)[0], max_new_tokens=4,
            temperature=1.0, top_k=10 * model.config.vocab_size,
        )
        assert wait_until(req.done.is_set, timeout=120)
        with pytest.raises(ValueError):
            server.submit(_prompts(model, [4], seed=13)[0],
                          max_new_tokens=4, top_k=-1)
    finally:
        server.close()
    assert req.status == "done"
    assert all(0 <= t < model.config.vocab_size for t in req.tokens)


def test_serve_loop_failure_fails_requests_not_hangs(lm):
    """If a tick raises, the loop must not die silently: every in-flight
    and queued request's waiter completes (cancelled) and new submissions
    are refused — the 'rejected, never hung' contract under engine failure."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=4),
        queue_depth=4,
    )
    prompts = _prompts(model, [4, 4], seed=12)
    reqs = [server.submit(p, max_new_tokens=4) for p in prompts]

    def boom():
        raise RuntimeError("injected tick failure")

    server.engine.tick = boom
    server.start()
    assert wait_until(lambda: all(r.done.is_set() for r in reqs), timeout=30)
    assert all(r.status == "cancelled" for r in reqs)
    with pytest.raises(RuntimeError):
        server.submit(prompts[0], max_new_tokens=2)
    server.close(drain=False)


def test_queued_deadline_expires_unserved(lm):
    """A queued request past its deadline is expired by the next tick —
    no prefill is spent on it and its waiter completes."""
    model, params = lm
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=4),
        queue_depth=4, registry=reg,
    )
    prompts = _prompts(model, [4], seed=5)
    req = server.submit(prompts[0], max_new_tokens=4, deadline_s=0.01)
    time.sleep(0.05)
    server.engine.tick()  # loop not running: drive one tick by hand
    assert req.done.is_set()
    assert req.status == "expired" and req.finish_reason == "deadline"
    assert req.tokens == []  # never admitted, never decoded
    recs = sink.of("serve_request")
    assert len(recs) == 1 and recs[0]["status"] == "expired"
    assert recs[0]["ttft_s"] is None
    server.close(drain=False)


def test_slow_host_injection_expires_running_request(lm):
    """PDT_TPU_FAULT=slow_host-style injection stretches tick time so a
    running request blows its deadline mid-decode — the deterministic
    chaos drill for the deadline path (no sleeps in the engine itself)."""
    from pytorch_distributed_training_tpu.faults.inject import (
        FaultPlan,
        set_plan,
    )
    from pytorch_distributed_training_tpu.telemetry.registry import (
        set_registry,
    )

    model, params = lm
    reg, sink = _registry()
    # install as the process default: the fault layer emits its
    # `fault_injected` record through get_registry(), not the engine handle
    prev_reg = set_registry(reg)
    prompts = _prompts(model, [4], seed=6)
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=64),
        queue_depth=2, registry=reg,
    )
    # warm compile OUTSIDE the injected-slowness window so the stretch
    # applies to steady decode ticks, not the one-off compile (2 tokens:
    # a 1-token request finishes at prefill and never compiles decode)
    warm = server.submit(prompts[0], max_new_tokens=2)
    while not warm.done.is_set():
        server.engine.tick()
    prev = set_plan(FaultPlan.parse("slow_host:200x"))
    try:
        req = server.submit(
            prompts[0], max_new_tokens=64, deadline_s=0.05
        )
        deadline = time.monotonic() + 60
        while not req.done.is_set() and time.monotonic() < deadline:
            server.engine.tick()
    finally:
        set_plan(prev)
        set_registry(prev_reg)
        server.close(drain=False)
    assert req.status == "expired" and req.finish_reason == "deadline"
    assert 0 < len(req.tokens) < 64  # partially decoded, then cut off
    assert sink.of("fault_injected")  # the injection itself is recorded


def test_clean_shutdown_cancels_in_flight(lm):
    """close(drain=False) with a request mid-decode and one still queued:
    both waiters complete as 'cancelled', the loop thread exits."""
    model, params = lm
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=64),
        queue_depth=4, registry=reg,
    ).start()
    prompts = _prompts(model, [4, 4], seed=8)
    running = server.submit(prompts[0], max_new_tokens=64)
    queued = server.submit(prompts[1], max_new_tokens=64)
    # wait until the first request is genuinely mid-decode
    assert wait_until(lambda: len(running.tokens) > 0, timeout=120)
    server.close(drain=False)
    assert running.done.is_set() and queued.done.is_set()
    assert running.status == "cancelled"
    assert 0 < len(running.tokens) < 64
    assert queued.status == "cancelled"
    # further submissions are refused once closed
    with pytest.raises(RuntimeError):
        server.submit(prompts[0], max_new_tokens=2)
    statuses = [r["status"] for r in sink.of("serve_request")]
    assert statuses.count("cancelled") == 2


def test_drain_shutdown_finishes_in_flight(lm):
    """close(drain=True) finishes queued + running work before stopping."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=8),
        queue_depth=4,
    ).start()
    prompts = _prompts(model, [4, 4, 4], seed=9)
    reqs = [server.submit(p, max_new_tokens=8) for p in prompts]
    server.close(drain=True)
    assert all(r.done.is_set() for r in reqs)
    assert all(r.status == "done" for r in reqs)
    assert all(len(r.tokens) == 8 for r in reqs)


def test_fifo_within_bucket_scheduling(lm):
    """Same-bucket requests are served strictly in submission order; the
    scheduler picks the earliest-submitted head across buckets."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(4, 8), max_new_tokens=2),
        queue_depth=8,
    )
    prompts = _prompts(model, [3, 7, 3, 7], seed=10)
    reqs = [server.submit(p, max_new_tokens=2) for p in prompts]
    order = []
    deadline = time.monotonic() + 120
    while not all(r.done.is_set() for r in reqs):
        server.engine.tick()
        for r in reqs:
            if r.admit_t is not None and r not in order:
                order.append(r)
        assert time.monotonic() < deadline
    assert order == reqs  # earliest-submitted first, within AND across buckets
    server.close(drain=False)


def test_engine_arms_watchdog_sections(lm):
    """Prefill and decode dispatch run under the installed watchdog — the
    hung-chip story covers serving exactly like training collectives."""
    import contextlib

    from pytorch_distributed_training_tpu.faults.watchdog import set_watchdog

    class StubWatchdog:
        def __init__(self):
            self.sections = []

        @contextlib.contextmanager
        def guard(self, what, step=None):
            self.sections.append(what)
            yield

    model, params = lm
    stub = StubWatchdog()
    prev = set_watchdog(stub)
    try:
        server = InferenceServer(
            model, params,
            EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=3),
            queue_depth=2,
        )
        req = server.submit(_prompts(model, [4], seed=11)[0], max_new_tokens=3)
        while not req.done.is_set():
            server.engine.tick()
        server.close(drain=False)
    finally:
        set_watchdog(prev)
    assert "serve_prefill" in stub.sections
    assert "serve_decode" in stub.sections


def test_serve_stdio_end_to_end(lm, tmp_path):
    """cli/serve_lm stdio mode: JSONL in, interleaved token/done events out,
    telemetry stream written, summarize_metrics folds a serving table."""
    from pytorch_distributed_training_tpu.cli.serve_lm import main

    mdir = tmp_path / "metrics"
    inp = io.StringIO("\n".join([
        json.dumps({"prompt": "hello world", "max_new_tokens": 3, "id": "a"}),
        json.dumps({"prompt": "the quick brown fox", "max_new_tokens": 3,
                    "id": "b"}),
        "not json",
        json.dumps({"prompt": 123, "id": "d"}),  # non-string prompt
        json.dumps({"prompt": "bye", "max_new_tokens": 3, "id": "c"}),
    ]) + "\n")
    out = io.StringIO()
    stats = main(
        ["--model", "gpt2-tiny", "--num-slots", "2",
         "--prompt-buckets", "16,32", "--max-new-tokens-cap", "8",
         "--metrics-dir", str(mdir)],
        in_stream=inp, out_stream=out,
    )
    events = [json.loads(l) for l in out.getvalue().splitlines()]
    done = {e["id"]: e for e in events if e.get("event") == "done"}
    assert set(done) == {"a", "b", "c"}
    assert all(d["status"] == "done" and d["new_tokens"] == 3
               for d in done.values())
    assert sum(1 for e in events if e.get("event") == "token") == 9
    # the non-JSON line and the non-string prompt each yield an error event
    assert sum(1 for e in events if e.get("event") == "error") == 2
    assert stats["admitted"] == 3 and stats["finished"] == 3

    # the JSONL stream folds into the serving percentile table
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(mdir), "--json"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    serve = json.loads(r.stdout)["serve"]
    assert serve["done"] == 3 and serve["tokens"] == 9
    assert serve["ttft_s"]["count"] == 3
    for key in ("p50", "p95", "p99"):
        assert serve["ttft_s"][key] is not None


def test_http_front_end(lm):
    """HTTP mode: /healthz, /stats, a streamed /generate, and 429 when the
    queue is full (loop deliberately stopped so fullness is deterministic)."""
    import http.client
    import threading

    from pytorch_distributed_training_tpu.data.bpe import ByteTokenizer
    from pytorch_distributed_training_tpu.serve import make_http_server

    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(16,), max_new_tokens=8),
        queue_depth=1,
    )
    httpd = make_http_server(server, ByteTokenizer())
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("GET", "/healthz")
        assert c.getresponse().status == 200
        c.close()

        # fill the (undrained) queue, then POST -> 429 backpressure
        filler = server.submit(
            np.arange(1, 5, dtype=np.int32), max_new_tokens=2
        )
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("POST", "/generate", body=json.dumps({"prompt": "hi"}))
        assert c.getresponse().status == 429
        c.close()

        # a non-string prompt is a 400, not a dropped connection
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("POST", "/generate", body=json.dumps({"prompt": 123}))
        assert c.getresponse().status == 400
        c.close()

        # drain by hand, then start the real loop for a streamed generation
        while not filler.done.is_set():
            server.engine.tick()
        server.start()
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        c.request(
            "POST", "/generate",
            body=json.dumps({"prompt": "hello", "max_new_tokens": 3}),
        )
        resp = c.getresponse()
        assert resp.status == 200
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        assert events[-1]["event"] == "done"
        assert events[-1]["new_tokens"] == 3
        assert [e for e in events if e["event"] == "token"]
        c.close()

        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("GET", "/stats")
        stats = json.loads(c.getresponse().read())
        assert stats["num_slots"] == 1
        c.close()
    finally:
        httpd.shutdown()
        server.close(drain=False)
