"""Fault-tolerance tests: the recovery paths, exercised for real.

The faults/ subsystem exists so "restart-from-checkpoint" is a tested
guarantee instead of a docstring claim (ISSUE 2). Unit tiers pin the spec
parser, checkpoint manifests, the watchdog state machine, the shutdown
handler, and the supervisor's jitter/window budget; the integration tier
drives the acceptance criteria end-to-end on the CPU mesh:

- ``crash_at_step`` + supervisor restart resumes and matches an
  uninterrupted run's params/opt_state bitwise;
- ``corrupt_ckpt:latest`` makes the next restore fall back to the newest
  VERIFIED step (and re-save over the damage when training passes it);
- ``sigterm_at_step`` produces an emergency checkpoint and the resumable
  exit code (75), and the run continues under ``--resume``.

All CPU-only, all tier-1 (``-m faults`` selects just this file's tier).
"""

import importlib.util
import json
import os
import shutil
import signal
import time

import jax
import numpy as np
import pytest

from pytorch_distributed_training_tpu.faults.inject import (
    FaultPlan,
    InjectedCrash,
    corrupt_step_dir,
    get_plan,
    set_plan,
)
from pytorch_distributed_training_tpu.faults.preemption import (
    RESUMABLE_EXIT_CODE,
    GracefulShutdown,
    Preempted,
)
from pytorch_distributed_training_tpu.faults.watchdog import (
    WATCHDOG_EXIT_CODE,
    Watchdog,
    set_watchdog,
    watchdog_guard,
)
from pytorch_distributed_training_tpu.telemetry import (
    JsonlSink,
    MetricsRegistry,
    set_registry,
)
from pytorch_distributed_training_tpu.train import manifest

pytestmark = pytest.mark.faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """No fault plan or watchdog leaks between tests (a leaked
    crash_at_step would fire inside an unrelated trainer run)."""
    yield
    set_plan(None)
    set_watchdog(None)


def _small_trainer(**tcfg_kw):
    """Tiny synthetic-task Trainer on the 4x2 CPU mesh (the
    test_trainer_integration recipe): 128 rows / batch 32 = 4 updates."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        MeshConfig,
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    defaults = dict(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        learning_rate=3e-3,
        warmup_steps=10,
        log_every=0,
        bf16=False,
        train_size=128,
        eval_size=32,
    )
    defaults.update(tcfg_kw)
    return Trainer(
        mcfg, TrainConfig(**defaults), MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="synthetic",
    )


def _flat(tree) -> np.ndarray:
    return np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(tree)]
    )


def _step_path(directory: str, step: int) -> str:
    import orbax.checkpoint as ocp

    return str(
        ocp.step.find_step_path(
            directory, ocp.step.standard_name_format(), step=step
        )
    )


def _records(path) -> list[dict]:
    return [json.loads(l) for l in open(path).read().splitlines()]


# ------------------------------------------------------------- spec parsing


def test_fault_spec_parsing_all_kinds():
    plan = FaultPlan.parse(
        "crash_at_step:7, sigterm_at_step:5, hang_at_step:3,"
        "corrupt_ckpt:latest, slow_host:2.5x, crash_at_step:9@1"
    )
    kinds = [(s.kind, s.rank) for s in plan.specs]
    assert kinds == [
        ("crash_at_step", 0), ("sigterm_at_step", 0), ("hang_at_step", 0),
        ("corrupt_ckpt", 0), ("slow_host", 0), ("crash_at_step", 1),
    ]
    assert plan.specs[0].step == 7
    assert plan.specs[3].target == "latest"
    assert plan.specs[4].factor == 2.5
    assert plan.specs[5].step == 9

    assert FaultPlan.parse(None).specs == []
    assert FaultPlan.parse("  ").specs == []
    assert FaultPlan.parse("corrupt_ckpt:12").specs[0].target == "12"


@pytest.mark.parametrize("bad", [
    "crash_at_step",          # no arg
    "explode_at_step:3",      # unknown kind
    "crash_at_step:0",        # step must be positive
    "corrupt_ckpt:newest",    # bad target
    "slow_host:0.5x",         # factor < 1
])
def test_fault_spec_parsing_rejects(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_crash_fault_fires_exactly_once():
    plan = FaultPlan.parse("crash_at_step:3")
    plan.fire_step_fault(2)  # not our step: nothing
    with pytest.raises(InjectedCrash):
        plan.fire_step_fault(3)
    # the restarted attempt re-walks step 3 in the SAME process: the spec
    # is spent, so the retry converges instead of crash-looping
    plan.fire_step_fault(3)


def test_slow_host_stays_armed_and_stretches():
    plan = FaultPlan.parse("slow_host:3x")
    t0 = time.perf_counter()
    plan.slow_host_delay(0.02)  # should sleep ~0.04 (2 extra x 0.02)
    plan.slow_host_delay(0.02)  # a straggler is slow EVERY batch
    assert time.perf_counter() - t0 >= 0.07
    # wrong rank: never fires
    other = FaultPlan.parse("slow_host:100x@1")
    t0 = time.perf_counter()
    other.slow_host_delay(1.0)
    assert time.perf_counter() - t0 < 0.5


def test_get_plan_parses_env_once(monkeypatch):
    monkeypatch.setenv("PDT_TPU_FAULT", "crash_at_step:11")
    set_plan(None)  # re-arm lazy parsing
    plan = get_plan()
    assert plan.specs[0].step == 11
    monkeypatch.setenv("PDT_TPU_FAULT", "crash_at_step:99")
    assert get_plan() is plan  # cached: fired-state survives restarts


# ---------------------------------------------------------------- manifests


def _fake_step_dir(tmp_path, name="step_7"):
    d = tmp_path / name
    d.mkdir()
    (d / "data.bin").write_bytes(b"A" * 1024)
    (d / "meta.json").write_text("{}")
    return str(d)


def test_manifest_roundtrip_and_size_verify(tmp_path):
    d = _fake_step_dir(tmp_path)
    m = manifest.build_manifest(d, 7, tree={"params['w']": {
        "shape": [4], "dtype": "float32"}})
    manifest.write_manifest(d, m)
    got = manifest.read_manifest(d)
    assert got["step"] == 7
    assert set(got["files"]) == {"data.bin", "meta.json"}
    assert got["files"]["data.bin"]["bytes"] == 1024
    assert got["tree"]["params['w']"]["shape"] == [4]
    assert got["versions"]["jax"]
    assert manifest.verify_step(d, level="size") == (True, "ok")
    assert manifest.verify_step(d, level="digest") == (True, "ok")


def test_manifest_size_catches_truncation(tmp_path):
    d = _fake_step_dir(tmp_path)
    manifest.write_manifest(d, manifest.build_manifest(d, 7))
    with open(os.path.join(d, "data.bin"), "r+b") as f:
        f.truncate(512)
    ok, reason = manifest.verify_step(d, level="size")
    assert not ok and "size mismatch" in reason


def test_manifest_digest_catches_same_size_corruption(tmp_path):
    d = _fake_step_dir(tmp_path)
    manifest.write_manifest(d, manifest.build_manifest(d, 7))
    corrupt_step_dir(d)  # flips bytes, same length
    assert manifest.verify_step(d, level="size") == (True, "ok")  # blind
    ok, reason = manifest.verify_step(d, level="digest")
    assert not ok and "digest mismatch" in reason


def test_manifest_missing_file_and_missing_manifest(tmp_path):
    d = _fake_step_dir(tmp_path)
    manifest.write_manifest(d, manifest.build_manifest(d, 7))
    os.remove(os.path.join(d, "meta.json"))
    ok, reason = manifest.verify_step(d, level="size")
    assert not ok and "missing" in reason

    bare = _fake_step_dir(tmp_path, "step_8")
    assert manifest.verify_step(bare, level="size")[1] == "no manifest"
    ok, reason = manifest.verify_step(bare, level="size", legacy_ok=True)
    assert ok and "legacy" in reason


def test_manifest_unreadable_is_corrupt_not_legacy(tmp_path):
    d = _fake_step_dir(tmp_path)
    with open(os.path.join(d, manifest.MANIFEST_NAME), "w") as f:
        f.write("{torn")
    ok, reason = manifest.verify_step(d, level="size")
    assert not ok and reason == "manifest unreadable"
    assert manifest.read_manifest(d) == {}  # present-but-broken, not None


def test_corrupt_step_dir_targets_largest_file_same_size(tmp_path):
    d = _fake_step_dir(tmp_path)
    before = open(os.path.join(d, "data.bin"), "rb").read()
    victim = corrupt_step_dir(d)
    assert victim.endswith("data.bin")  # the largest file
    after = open(victim, "rb").read()
    assert len(after) == len(before) and after != before


# ----------------------------------------------------------------- watchdog


def _reg_with_sink(tmp_path):
    reg = MetricsRegistry()
    sink = JsonlSink(str(tmp_path), process_index=0)
    reg.attach_sink(sink)
    return reg, sink


def test_watchdog_stall_and_recover_records(tmp_path):
    reg, sink = _reg_with_sink(tmp_path)
    prev = set_registry(reg)
    wd = Watchdog(stall_factor=10.0, min_stall_s=0.05, hard_timeout_s=0)
    try:
        with wd.guard("slow_section", step=3):
            time.sleep(0.25)
    finally:
        wd.close()
        set_registry(prev)
        sink.close()
    recs = _records(tmp_path / "metrics.jsonl")
    stall = [r for r in recs if r["record"] == "watchdog_stall"]
    rec = [r for r in recs if r["record"] == "watchdog_recovered"]
    assert len(stall) == 1 and len(rec) == 1
    assert stall[0]["section"] == "slow_section" and stall[0]["step"] == 3
    # the stack dump names this test — the "which collective, from where"
    # post-mortem the record exists for
    assert "test_watchdog_stall" in stall[0]["stacks"]
    assert rec[0]["duration_s"] >= 0.25


def test_watchdog_hard_timeout_aborts_with_exit_code(tmp_path):
    exits = []
    reg, sink = _reg_with_sink(tmp_path)
    prev = set_registry(reg)
    wd = Watchdog(
        stall_factor=10.0, min_stall_s=0.02, hard_timeout_s=0.1,
        _exit=exits.append,
    )
    try:
        with wd.guard("hung_collective"):
            t0 = time.monotonic()
            while not exits and time.monotonic() - t0 < 10:
                time.sleep(0.01)  # a wedged section never returns on its own
    finally:
        wd.close()
        set_registry(prev)
        sink.close()
    assert exits == [WATCHDOG_EXIT_CODE]
    recs = _records(tmp_path / "metrics.jsonl")
    kinds = [r["record"] for r in recs]
    assert "watchdog_stall" in kinds and "watchdog_abort" in kinds
    abort = next(r for r in recs if r["record"] == "watchdog_abort")
    assert abort["section"] == "hung_collective"
    assert abort["exit_code"] == WATCHDOG_EXIT_CODE
    assert abort["stacks"]


def test_watchdog_threshold_tracks_rolling_median():
    wd = Watchdog(stall_factor=4.0, min_stall_s=0.5, hard_timeout_s=0)
    assert wd.stall_after_s("step") == 0.5  # no history: the floor
    for s in (1.0, 1.0, 1.0, 30.0):  # median robust to one outlier
        wd.observe("step", s)
    assert wd.stall_after_s("step") == pytest.approx(4.0)
    wd.close()


def test_watchdog_rejects_bad_thresholds():
    with pytest.raises(ValueError, match="watchdog"):
        Watchdog(stall_factor=0)


def test_watchdog_guard_without_install_is_noop():
    assert set_watchdog(None) is None  # nothing installed
    with watchdog_guard("anything"):
        pass  # must not arm, spawn threads, or raise


# --------------------------------------------------------------- preemption


def test_graceful_shutdown_flag_install_uninstall():
    gs = GracefulShutdown(handle_sigint=False)
    before = signal.getsignal(signal.SIGINT)
    with gs:
        assert gs.installed
        assert signal.getsignal(signal.SIGINT) is before  # SIGINT untouched
        assert signal.getsignal(signal.SIGTERM) == gs._handle
        assert gs.requested is None
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)  # handler runs at the next bytecode boundary
        assert gs.requested == signal.SIGTERM  # flag only — no raise
    assert not gs.installed
    # uninstalled: a later SIGTERM hits whatever was there before, not gs
    assert signal.getsignal(signal.SIGTERM) != gs._handle


def test_preempted_carries_resumable_exit_code():
    exc = Preempted(signal.SIGTERM, step=12)
    assert isinstance(exc, SystemExit)  # untouched, it EXITS with the code
    assert exc.code == RESUMABLE_EXIT_CODE == 75
    assert exc.step == 12
    assert "SIGTERM" in str(exc) and "75" in str(exc)


# --------------------------------------------------------------- supervisor


class _FakeTime:
    """Deterministic clock for the supervisor: sleep() advances monotonic()."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def monotonic(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


@pytest.fixture
def fake_time(monkeypatch):
    from pytorch_distributed_training_tpu.utils import supervisor

    ft = _FakeTime()
    monkeypatch.setattr(supervisor, "time", ft)
    return ft


def test_supervisor_jitter_stays_in_bounds(fake_time):
    import random

    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        if i < 4:
            raise RuntimeError("flaky host")
        return "ok"

    out = run_with_restarts(
        attempt, max_restarts=4, backoff_s=2.0, backoff_factor=3.0,
        max_backoff_s=10.0, _rng=random.Random(0),
    )
    assert out == "ok" and calls == [0, 1, 2, 3, 4]
    assert len(fake_time.sleeps) == 4
    # decorrelated jitter: every delay in [backoff_s, max_backoff_s], and
    # the schedule is not the deterministic 2/6/18/... lockstep ramp
    for s in fake_time.sleeps:
        assert 2.0 <= s <= 10.0
    assert fake_time.sleeps != [2.0, 6.0, 10.0, 10.0]


def test_supervisor_lifetime_budget_exhausts(fake_time):
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        raise RuntimeError("deterministic bug")

    with pytest.raises(RuntimeError, match="deterministic bug"):
        run_with_restarts(attempt, max_restarts=2, backoff_s=1.0, jitter=False)
    assert calls == [0, 1, 2]  # the budget bounds a crash loop


def test_supervisor_sliding_window_lets_old_restarts_expire(fake_time):
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        if i < 4:
            raise RuntimeError("occasional failure")
        return "done"

    # 4 failures spaced 5s (the backoff) apart with a 2-restart budget per
    # 8s window: each failure sees at most one unexpired restart, so a long
    # run survives them all — where the lifetime budget above died at 3
    out = run_with_restarts(
        attempt, max_restarts=2, backoff_s=5.0, backoff_factor=1.0,
        jitter=False, restart_window_s=8.0,
    )
    assert out == "done" and calls == [0, 1, 2, 3, 4]


def test_supervisor_window_still_stops_a_crash_loop(fake_time):
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        raise RuntimeError("tight crash loop")

    with pytest.raises(RuntimeError):
        run_with_restarts(
            attempt, max_restarts=2, backoff_s=1.0, backoff_factor=1.0,
            jitter=False, restart_window_s=100.0,
        )
    assert calls == [0, 1, 2]  # both in-window slots burned, then raise


def test_supervisor_preempted_propagates_without_burning_a_restart(fake_time):
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        raise Preempted(signal.SIGTERM, step=3)

    with pytest.raises(Preempted) as exc:
        run_with_restarts(attempt, max_restarts=5, backoff_s=1.0)
    assert exc.value.code == RESUMABLE_EXIT_CODE
    assert calls == [0]  # no retry: the host is going away
    assert fake_time.sleeps == []


# ------------------------------------------------- checkpoint integrity (IT)


@pytest.fixture(scope="module")
def mini_run(eight_devices, tmp_path_factory):
    """One uninterrupted checkpointed run: 4 updates, saves at steps 2 and
    4 — the shared baseline for the integrity and recovery tests."""
    tmp = tmp_path_factory.mktemp("faults_baseline")
    d = str(tmp / "ckpt")
    trainer = _small_trainer(checkpoint_dir=d, checkpoint_every_steps=2)
    trainer.run()
    assert int(jax.device_get(trainer.state.step)) == 4
    return trainer, d


def test_manifests_written_and_verified_latest_step(mini_run):
    from pytorch_distributed_training_tpu.train.checkpoint import (
        latest_step,
        verified_latest_step,
    )

    _, d = mini_run
    assert latest_step(d) == 4
    assert verified_latest_step(d) == 4
    assert verified_latest_step(d, level="digest") == 4
    for step in (2, 4):
        sp = _step_path(d, step)
        assert os.path.exists(os.path.join(sp, manifest.MANIFEST_NAME))
        assert manifest.verify_step(sp, level="digest") == (True, "ok")


def test_duplicate_save_skips_with_counter(mini_run, tmp_path):
    from pytorch_distributed_training_tpu.train.checkpoint import Checkpointer

    trainer, _ = mini_run
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        cp = Checkpointer(str(tmp_path / "dup"))
        cp.save(trainer.state)
        cp.wait()
        cp.save(trainer.state)  # resume-then-periodic-save collision
        cp.close()
    finally:
        set_registry(prev)
    snap = reg.snapshot()
    assert snap["counters"]["checkpoint/saves"] == 1  # one real save
    assert snap["counters"]["checkpoint/duplicate_skips"] == 1  # no crash


def test_restore_falls_back_to_newest_verified_step(mini_run, tmp_path):
    from pytorch_distributed_training_tpu.train.checkpoint import Checkpointer

    trainer, d = mini_run
    work = str(tmp_path / "ckpt")
    shutil.copytree(d, work)
    corrupt_step_dir(_step_path(work, 4))  # same-size damage

    cp = Checkpointer(work, verify="digest")
    assert cp.latest_step() == 4  # orbax still lists the corrupt step
    assert cp.verified_latest_step() == 2  # what restore will actually use
    restored = cp.restore(trainer.state)
    cp.close()
    assert int(jax.device_get(restored.step)) == 2


def test_restore_raises_when_nothing_verifies(mini_run, tmp_path):
    from pytorch_distributed_training_tpu.train.checkpoint import (
        CheckpointCorruptError,
        Checkpointer,
    )

    trainer, d = mini_run
    work = str(tmp_path / "ckpt")
    shutil.copytree(d, work)
    for step in (2, 4):
        corrupt_step_dir(_step_path(work, step))
    cp = Checkpointer(work, verify="digest")
    assert cp.verified_latest_step() is None
    with pytest.raises(CheckpointCorruptError, match="digest mismatch"):
        cp.restore(trainer.state)
    cp.close()


def test_restore_accepts_manifestless_legacy_dir(mini_run, tmp_path):
    from pytorch_distributed_training_tpu.train.checkpoint import Checkpointer

    trainer, d = mini_run
    work = str(tmp_path / "ckpt")
    shutil.copytree(d, work)
    for step in (2, 4):  # a pre-manifest-era directory
        os.remove(os.path.join(_step_path(work, step), manifest.MANIFEST_NAME))
    cp = Checkpointer(work)
    restored = cp.restore(trainer.state)  # latest, with a warning — not a crash
    cp.close()
    assert int(jax.device_get(restored.step)) == 4


def _load_verifier():
    spec = importlib.util.spec_from_file_location(
        "verify_checkpoint",
        os.path.join(REPO_ROOT, "scripts", "verify_checkpoint.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_verify_checkpoint_script_exit_codes(mini_run, tmp_path, capsys):
    """Distinct exit codes so publishers/CI gate without parsing:
    0 verified / 2 partial (fallback exists) / 3 corrupt (nothing
    verifies) / 4 missing (no directory, no checkpoint, no such step)."""
    vc = _load_verifier()
    _, d = mini_run
    work = str(tmp_path / "ckpt")
    shutil.copytree(d, work)

    assert vc.main([work]) == 0  # clean dir: everything verifies
    assert vc.main([work, "--strict"]) == 0
    out = capsys.readouterr().out
    assert "2/2 step(s) verified" in out

    corrupt_step_dir(_step_path(work, 4))
    assert vc.main([work]) == 0  # size-level is blind to same-size damage
    assert vc.main([work, "--strict"]) == 2  # fallback step exists
    out = capsys.readouterr().out
    assert "restore would use: 2" in out
    assert vc.main([work, "--strict", "--step", "2"]) == 0  # single step
    assert vc.main([work, "--step", "7"]) == 4  # no such step

    corrupt_step_dir(_step_path(work, 2))
    assert vc.main([work, "--strict", "--quiet"]) == 3  # corrupt: none left

    empty = tmp_path / "empty"
    empty.mkdir()
    assert vc.main([str(empty)]) == 4
    assert vc.main([str(tmp_path / "missing")]) == 4


def test_verify_checkpoint_script_json_report(mini_run, tmp_path, capsys):
    """--json: per-step verdicts + the per-file digests each manifest
    records — what an external publisher signs off on before a step may
    enter a serving fleet's hot-swap rotation."""
    vc = _load_verifier()
    _, d = mini_run
    work = str(tmp_path / "ckpt")
    shutil.copytree(d, work)

    assert vc.main([work, "--strict", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == "verified"
    assert report["verified"] == report["total"] == 2
    assert report["verified_latest"] == 4
    by_step = {s["step"]: s for s in report["steps"]}
    assert by_step[4]["ok"] is True and by_step[4]["reason"] == "ok"
    digests = by_step[4]["digests"]
    assert digests and all(
        isinstance(v, str) and len(v) == 64 for v in digests.values()
    )

    corrupt_step_dir(_step_path(work, 4))
    assert vc.main([work, "--strict", "--json"]) == 2
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == "partial"
    assert report["verified_latest"] == 2
    by_step = {s["step"]: s for s in report["steps"]}
    assert by_step[4]["ok"] is False
    assert "digest mismatch" in by_step[4]["reason"]

    assert vc.main([str(tmp_path / "missing"), "--json"]) == 4
    report = json.loads(capsys.readouterr().out)
    assert report["verdict"] == "missing" and report["steps"] == []


def test_write_manifest_fsyncs_named_files_before_seal(
    tmp_path, monkeypatch
):
    """Torn-publish durability: before the seal rename lands, every data
    file the manifest names (and the directories holding them) must be
    fsynced, and the rename itself fsynced after — a host crash
    mid-publish can never leave a manifest naming arrays that were not
    durably written (the hot-swap watcher acts on the seal alone)."""
    step_path = tmp_path / "7"
    sub = step_path / "arrays"
    sub.mkdir(parents=True)
    (step_path / "meta.json").write_bytes(b"{}")
    (sub / "w.bin").write_bytes(b"weights")

    synced = []
    real_fsync = os.fsync

    def spy_fsync(fd):
        synced.append(os.readlink(f"/proc/self/fd/{fd}"))
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    m = manifest.build_manifest(str(step_path), 7)
    manifest.write_manifest(str(step_path), m)

    assert set(m["files"]) == {"meta.json", os.path.join("arrays", "w.bin")}
    # every named data file was fsynced...
    for rel in m["files"]:
        assert str(step_path / rel) in synced
    # ...and so were the directories (file creation durability) and the
    # step dir again after the rename (seal durability); the manifest tmp
    # itself is the deleted-on-rename entry
    assert synced.count(str(step_path)) >= 2
    assert str(sub) in synced
    assert manifest.verify_step(str(step_path), level="digest") == (
        True, "ok",
    )


# --------------------------------------------------- end-to-end recovery (IT)


def test_crash_at_step_supervised_restart_resumes_bitwise(
    mini_run, eight_devices, tmp_path
):
    """Acceptance: crash after update 3, supervisor restarts, the resumed
    attempt restores the step-2 checkpoint and must land on the SAME final
    params and opt_state as the uninterrupted baseline — bitwise."""
    import dataclasses

    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    baseline, _ = mini_run
    d = str(tmp_path / "ckpt")
    attempts = []
    prev = set_plan(FaultPlan.parse("crash_at_step:3"))
    try:
        def attempt(i):
            attempts.append(i)
            trainer = _small_trainer(
                checkpoint_dir=d, checkpoint_every_steps=2, resume=i > 0
            )
            trainer.run()
            return trainer

        trainer = run_with_restarts(
            attempt, max_restarts=1, backoff_s=0.01, jitter=False,
            checkpoint_dir=d,
        )
    finally:
        set_plan(prev)
    assert attempts == [0, 1]  # one injected crash, one successful resume
    assert int(jax.device_get(trainer.state.step)) == 4
    np.testing.assert_array_equal(
        _flat(trainer.state.params), _flat(baseline.state.params)
    )
    np.testing.assert_array_equal(
        _flat(trainer.state.opt_state), _flat(baseline.state.opt_state)
    )


def test_sigterm_emergency_checkpoint_and_resumable_exit(
    eight_devices, tmp_path
):
    """Acceptance: SIGTERM mid-epoch → emergency checkpoint inside the
    grace window, a `preemption` telemetry record, exit code 75 — and the
    relaunched run resumes to completion."""
    from pytorch_distributed_training_tpu.train.checkpoint import (
        verified_latest_step,
    )

    d = str(tmp_path / "ckpt")
    mdir = str(tmp_path / "metrics")
    prev = set_plan(FaultPlan.parse("sigterm_at_step:2"))
    try:
        trainer = _small_trainer(checkpoint_dir=d, metrics_dir=mdir)
        with pytest.raises(Preempted) as exc:
            trainer.run()
    finally:
        set_plan(prev)
    assert exc.value.code == RESUMABLE_EXIT_CODE
    # the emergency save landed, committed, and verifies
    assert verified_latest_step(d, level="digest") == 2

    recs = _records(os.path.join(mdir, "metrics.jsonl"))
    pre = [r for r in recs if r["record"] == "preemption"]
    assert len(pre) == 1
    assert pre[0]["signal"] == signal.SIGTERM
    assert pre[0]["saved_step"] == 2
    assert pre[0]["save_wall_s"] <= pre[0]["grace_s"]
    assert any(r["record"] == "fault_injected" for r in recs)

    # "resumable" is a promise: relaunching with resume continues to the end
    resumed = _small_trainer(checkpoint_dir=d, resume=True)
    assert int(jax.device_get(resumed.state.step)) == 2
    history = resumed.run()
    assert int(jax.device_get(resumed.state.step)) == 4
    assert len(history) == 1


def test_corrupt_ckpt_injection_falls_back_then_heals(
    eight_devices, tmp_path
):
    """Acceptance: a run whose LATEST checkpoint is corrupted restores from
    the newest verified step instead of crashing — and when training passes
    the damaged step again, the duplicate-save guard re-saves over it
    instead of skipping, so the directory heals."""
    d = str(tmp_path / "ckpt")
    mdir = str(tmp_path / "metrics")
    prev = set_plan(FaultPlan.parse("corrupt_ckpt:latest"))
    try:
        first = _small_trainer(
            checkpoint_dir=d, checkpoint_every_steps=2,
            checkpoint_verify="digest",
        )
        first.run()  # Checkpointer.close() fires the injection on step 4
    finally:
        set_plan(prev)
    assert manifest.verify_step(_step_path(d, 4), level="digest")[0] is False

    resumed = _small_trainer(
        checkpoint_dir=d, checkpoint_every_steps=2,
        checkpoint_verify="digest", resume=True, metrics_dir=mdir,
    )
    # restore skipped the corrupt step 4 for verified step 2
    assert int(jax.device_get(resumed.state.step)) == 2
    resumed.run()
    assert int(jax.device_get(resumed.state.step)) == 4

    recs = _records(os.path.join(mdir, "metrics.jsonl"))
    fb = [r for r in recs if r["record"] == "checkpoint_fallback"]
    assert fb and fb[0]["latest_step"] == 4 and fb[0]["fallback_step"] == 2
    # the re-trained step 4 replaced the damaged copy (checkpoint_resave)
    assert any(r["record"] == "checkpoint_resave" for r in recs)
    assert manifest.verify_step(_step_path(d, 4), level="digest") == (
        True, "ok",
    )
    np.testing.assert_array_equal(
        _flat(resumed.state.params), _flat(first.state.params)
    )


def test_hang_injection_wedges_until_watchdog_abort(tmp_path):
    """hang_at_step blocks forever inside a watchdog-guarded section — the
    failure that never raises. Driven in a daemon thread (a real run dies
    by ``os._exit``; in-process we inject the exit and assert the code +
    the abort record). The wedged thread stays parked, like a real hang."""
    import threading

    reg, sink = _reg_with_sink(tmp_path)
    prev_reg = set_registry(reg)
    aborted = threading.Event()
    exits = []

    def fake_exit(code):
        exits.append(code)
        aborted.set()

    wd = Watchdog(
        stall_factor=1.0, min_stall_s=0.05, hard_timeout_s=0.2,
        _exit=fake_exit,
    )
    prev_wd = set_watchdog(wd)
    plan = FaultPlan.parse("hang_at_step:2")
    hang = threading.Thread(
        target=plan.fire_step_fault, args=(2,), daemon=True
    )
    try:
        hang.start()
        assert aborted.wait(timeout=10), "watchdog never aborted the hang"
    finally:
        set_watchdog(prev_wd)
        wd.close()
        set_registry(prev_reg)
        sink.close()
    assert exits == [WATCHDOG_EXIT_CODE]
    recs = _records(tmp_path / "metrics.jsonl")
    assert any(r["record"] == "fault_injected" for r in recs)
    abort = next(r for r in recs if r["record"] == "watchdog_abort")
    assert abort["section"] == "injected_hang" and abort["step"] == 2


# ------------------------------------------------------------- CLI contract


def test_run_supervised_validates_restart_contract(mini_run):
    """The shared CLI glue: --max-restarts demands a checkpoint dir, and a
    dir already holding a checkpoint demands an explicit --resume (a retry
    would otherwise silently continue a DIFFERENT run's trajectory)."""
    from types import SimpleNamespace

    from pytorch_distributed_training_tpu.cli import run_supervised
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    _, d = mini_run
    args = SimpleNamespace(max_restarts=1, restart_window_s=0.0)

    with pytest.raises(SystemExit, match="checkpoint-dir"):
        run_supervised(args, TrainConfig(), lambda cfg: None)
    with pytest.raises(SystemExit, match="already holds"):
        run_supervised(
            args, TrainConfig(checkpoint_dir=d), lambda cfg: None
        )

    # resume makes the stale-dir guard moot; retries flip resume on
    seen = []

    def build(cfg):
        seen.append(cfg.resume)
        return SimpleNamespace(run=lambda: "history")

    out = run_supervised(
        args, TrainConfig(checkpoint_dir=d, resume=True), build
    )
    assert out == "history" and seen == [True]
