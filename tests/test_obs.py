"""Observability-plane tests (telemetry/spans.py, telemetry/flight.py,
telemetry/slo.py + their serve-stack instrumentation and the
trace_view/summarize renderers). CPU, tier-1.

Four layers:

- pure unit tests with injected clocks (burn-rate window math, flight-ring
  bounds, span/trace structural analysis) — no sleeps, no sockets;
- in-process engine tests (gpt2-tiny): the span tree a served request
  emits TILES its lifetime, mixed greedy/speculative; a PDT_TPU_FAULT
  replica_hang under an installed watchdog dumps the flight ring with the
  stalled tick as the last entry;
- stub-replica router tests: hedged/retried attempts stay in ONE trace,
  and the X-Parent-Span header the router sends names the attempt/hedge
  span the replica should parent under;
- one subprocess drill: a REAL replica (cli/serve_lm.py) writes its span
  stream to disk, the merged coordinator+replica streams reconstruct the
  request end-to-end across the process boundary, and SIGTERM drain dumps
  the replica's flight ring.
"""

import http.client
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.router import (
    Router,
    RouterConfig,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.telemetry.flight import (
    FlightRecorder,
)
from pytorch_distributed_training_tpu.telemetry import flight as flight_mod
from pytorch_distributed_training_tpu.telemetry.slo import (
    BurnRateMonitor,
    SloConfig,
    burn_rate,
)
from pytorch_distributed_training_tpu.telemetry.spans import (
    REQUEST_PHASES,
    Tracer,
    spans_by_trace,
    trace_coverage,
    trace_summary,
)

pytestmark = [pytest.mark.serve, pytest.mark.obs]


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        with self._lock:
            self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        with self._lock:
            return [r for r in self.records if r.get("record") == kind]


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


@pytest.fixture(autouse=True)
def _clean_fault_state():
    from pytorch_distributed_training_tpu.faults.inject import set_plan
    from pytorch_distributed_training_tpu.faults.watchdog import set_watchdog

    yield
    set_plan(None)
    set_watchdog(None)


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


def _load_script(name):
    """Import a scripts/*.py module by path (scripts/ is not a package)."""
    path = os.path.join(
        os.path.dirname(__file__), "..", "scripts", f"{name}.py"
    )
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# =====================================================================
# span plane: the tree a served request emits
# =====================================================================


def test_span_tree_tiles_request_mixed_greedy_spec(lm):
    """Every accepted request — greedy and speculative in the same batch —
    yields ONE complete span tree whose queue/prefill/decode phases tile
    submit→finish exactly (the bench's 5% reconciliation gate is met by
    construction, asserted here with zero tolerance on the stamps)."""
    model, params = lm
    reg, sink = _registry()
    prompts = _prompts(model, [4, 6, 5, 7], seed=3)
    T = 6
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=T,
            kv_layout="paged", sampling="device", spec_k=3,
        ),
        queue_depth=16, registry=reg,
    ).start()
    spec_flags = [False, True, False, True]
    tiers = ["interactive", "batch", "interactive", "batch"]
    try:
        reqs = [
            server.submit(p, max_new_tokens=T, spec=s, tier=t)
            for p, s, t in zip(prompts, spec_flags, tiers)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs), [r.status for r in reqs]

    cov = trace_coverage(sink.records, accepted_ids=[r.id for r in reqs])
    assert cov["traces"] == 4
    assert cov["coverage"] == 1.0, cov
    assert cov["orphan_spans"] == 0 and cov["incomplete"] == []
    assert cov["phase_sum_bad"] == []

    traces = spans_by_trace(sink.records)
    for req, spec, tier in zip(reqs, spec_flags, tiers):
        spans = {s["name"]: s for s in traces[req.id]}
        assert {"serve", "queue", "prefill", "decode"} <= set(spans)
        serve = spans["serve"]
        # in-process submit: no router above us, serve IS the root
        assert serve["parent"] is None
        assert serve["attrs"]["tier"] == tier
        assert serve["attrs"]["status"] == "done"
        assert "weights_step" in serve["attrs"]
        # exact tiling: each phase starts where the previous one ended
        assert spans["queue"]["t0_s"] == serve["t0_s"]
        assert spans["queue"]["t1_s"] == spans["prefill"]["t0_s"]
        assert spans["prefill"]["t1_s"] == spans["decode"]["t0_s"]
        assert spans["decode"]["t1_s"] == serve["t1_s"]
        assert trace_summary(traces[req.id])["phase_sum_ok"] is True
        # page-reservation span nests under prefill, not the root
        assert spans["admission"]["parent"] == spans["prefill"]["span"]
        assert spans["decode"]["attrs"]["tokens"] == T
        if spec:
            assert spans["decode"]["attrs"]["drafted"] > 0
            assert spans["decode"]["attrs"]["accepted"] >= 0


# =====================================================================
# router side: hedges/retries stay in ONE trace
# =====================================================================


class StubReplica:
    """Replica-shaped HTTP stub that captures the trace headers it gets.

    ``mode``: "ok" (stream then done) or "slow" (sleep ``ttfb_s`` first —
    the hedge trigger). Every POST records ``(X-Request-Id,
    X-Parent-Span)`` into ``seen`` before any behavior kicks in."""

    def __init__(self, *, mode="ok", tokens=3, ttfb_s=0.0, queue_depth=0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        stub = self
        self.mode = mode
        self.tokens = tokens
        self.ttfb_s = ttfb_s
        self.queue_depth = queue_depth
        self.seen = []
        self._seen_lock = threading.Lock()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                body = (json.dumps({
                    "state": "ready", "queue_depth": stub.queue_depth,
                    "slot_occupancy": 0.0, "num_slots": 1,
                }) + "\n").encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                rid = self.headers.get("X-Request-Id", "?")
                with stub._seen_lock:
                    stub.seen.append(
                        (rid, self.headers.get("X-Parent-Span"))
                    )
                if stub.mode == "slow":
                    time.sleep(stub.ttfb_s)
                self.send_response(200)
                self.end_headers()
                for i in range(stub.tokens):
                    self.wfile.write((json.dumps({
                        "id": rid, "event": "token", "token_id": i,
                    }) + "\n").encode())
                    self.wfile.flush()
                self.wfile.write((json.dumps({
                    "id": rid, "event": "done", "status": "done",
                    "new_tokens": stub.tokens,
                }) + "\n").encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    def close(self):
        self.httpd.shutdown()


def test_hedged_attempts_share_one_trace(lm=None):
    """A hedged request emits request→attempt→hedge spans under ONE trace
    id (the X-Request-Id), and the X-Parent-Span header each replica saw
    names exactly the router span it should parent its serve span under —
    the cross-process causality link, asserted at the wire."""
    reg, sink = _registry()
    # the empty-queue slow stub is picked first; the loaded fast one is
    # the hedge target after hedge_s with no first byte
    slow = StubReplica(mode="slow", ttfb_s=3.0, queue_depth=0)
    fast = StubReplica(mode="ok", tokens=2, queue_depth=5)
    router = Router(
        [("s0", "127.0.0.1", slow.port), ("s1", "127.0.0.1", fast.port)],
        RouterConfig(
            health_interval_s=0.03, health_timeout_s=0.5,
            breaker_threshold=3, breaker_cooldown_s=0.25,
            retry_backoff_s=0.01, retry_backoff_max_s=0.05,
            ttfb_timeout_s=5.0, hedge_s=0.1,
        ),
        registry=reg,
    ).start()
    try:
        assert wait_until(
            lambda: router.available_count() >= 2, timeout=5
        ), router.stats()
        lines = []
        out = router.route_generate(
            json.dumps({"prompt": "x"}).encode(), "obs-hedge-1",
            lambda b: lines.append(json.loads(b)),
        )
        assert out["status"] == "ok" and out["hedged"] is True
    finally:
        router.close()
        slow.close()
        fast.close()

    traces = spans_by_trace(sink.records)
    assert list(traces) == ["obs-hedge-1"]   # hedge did NOT fork a trace
    spans = {s["name"]: s for s in traces["obs-hedge-1"]}
    assert {"request", "attempt", "hedge"} <= set(spans)
    summary = trace_summary(traces["obs-hedge-1"])
    assert summary["complete"] is True and summary["roots"] == 1
    assert spans["request"]["parent"] is None
    assert spans["attempt"]["parent"] == spans["request"]["span"]
    assert spans["hedge"]["parent"] == spans["attempt"]["span"]
    assert spans["request"]["attrs"]["hedged"] is True

    # the wire contract: the primary carried the attempt span id, the
    # hedge carried the hedge span id, both under the same request id
    assert slow.seen == [("obs-hedge-1", spans["attempt"]["span"])]
    assert fast.seen == [("obs-hedge-1", spans["hedge"]["span"])]


# =====================================================================
# flight recorder: ring bounds + post-mortem dumps
# =====================================================================


def test_flight_recorder_ring_dump_and_registry():
    reg, sink = _registry()
    fr = FlightRecorder(4, component="unit", registry=reg)
    for i in range(10):
        fr.record(tick=i, payload=i * 2)
    snap = fr.snapshot()
    assert [e["seq"] for e in snap] == [7, 8, 9, 10]   # bounded, newest
    assert snap[-1] == {"seq": 10, "tick": 9, "payload": 18}

    rec = fr.dump("unit_test", attrs={"extra": 1})
    assert rec["record"] == "flight_dump" and rec["reason"] == "unit_test"
    assert rec["depth"] == 4 and rec["dropped"] == 6 and rec["extra"] == 1
    assert rec["entries"][-1]["tick"] == 9
    assert sink.of("flight_dump")[-1]["reason"] == "unit_test"
    assert fr.stats()["flight_dumps"] == 1
    assert fr.stats()["flight_last_dump"] == "unit_test"

    # process-wide hookup: registered rings answer dump_all, unregistered
    # rings are left alone (a closed server must not keep dumping)
    flight_mod.register(fr)
    try:
        assert flight_mod.dump_all("drill") >= 1
        assert fr.dumps == 2
    finally:
        flight_mod.unregister(fr)
    flight_mod.dump_all("after_unregister")
    assert fr.dumps == 2

    with pytest.raises(ValueError):
        FlightRecorder(0)


def test_watchdog_stall_dumps_flight_with_stalled_tick(lm):
    """An injected replica_hang under an installed watchdog produces a
    ``flight_dump`` whose LAST entry is the stalled tick itself — the
    acceptance criterion for the black-box: the run-up to the wedge is on
    the record, ending at the wedge."""
    from pytorch_distributed_training_tpu.faults.inject import (
        FaultPlan,
        set_plan,
    )
    from pytorch_distributed_training_tpu.faults.watchdog import (
        Watchdog,
        set_watchdog,
    )
    from pytorch_distributed_training_tpu.telemetry.registry import (
        set_registry,
    )

    model, params = lm
    reg, sink = _registry()
    prev_reg = set_registry(reg)
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=12),
        queue_depth=8, registry=reg,
    ).start()
    wd = None
    prev_plan = prev_wd = None
    try:
        # warm OUTSIDE the watchdog: compile ticks are slow and would
        # poison the stall threshold's history
        warm = server.submit(
            _prompts(model, [4], seed=1)[0], max_new_tokens=12
        )
        assert wait_until(warm.done.is_set, timeout=120)

        hang_tick = server.engine.busy_ticks + 3
        wd = Watchdog(stall_factor=5.0, min_stall_s=0.1, hard_timeout_s=0)
        prev_wd = set_watchdog(wd)
        prev_plan = set_plan(
            FaultPlan.parse(f"replica_hang:{hang_tick}:1.0")
        )
        req = server.submit(
            _prompts(model, [4], seed=2)[0], max_new_tokens=8
        )
        assert wait_until(req.done.is_set, timeout=120)
        assert req.status == "done"
        assert wait_until(
            lambda: sink.of("flight_dump"), timeout=10
        ), "watchdog never dumped the flight ring"
    finally:
        set_plan(prev_plan)
        if wd is not None:
            wd.close()
            set_watchdog(prev_wd)
        server.close(drain=False)
        set_registry(prev_reg)

    stalls = sink.of("watchdog_stall")
    assert stalls and stalls[0]["section"] == "serve_tick"
    dumps = [
        r for r in sink.of("flight_dump")
        if r["reason"] == "watchdog_stall"
    ]
    assert dumps, sink.of("flight_dump")
    entries = dumps[0]["entries"]
    assert entries, "dump carried an empty ring"
    # the hang fires at the END of busy tick `hang_tick`, whose flight
    # entry was recorded just before the chaos hook — so the ring's last
    # entry IS the stalled tick
    assert entries[-1]["busy_tick"] == hang_tick
    assert dumps[0]["component"] == "engine"
    # the injected fault itself is on the record too
    faults = sink.of("fault_injected")
    assert any(r.get("fault") == "replica_hang" for r in faults)


def test_debug_flight_endpoint(lm):
    """GET /debug/flight on a live replica returns the ring AND leaves a
    flight_dump record on the metrics stream (on-demand post-mortem)."""
    from pytorch_distributed_training_tpu.data.bpe import ByteTokenizer
    from pytorch_distributed_training_tpu.serve import make_http_server

    model, params = lm
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=4),
        queue_depth=4, registry=reg,
    ).start()
    httpd = make_http_server(server, ByteTokenizer())
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        req = server.submit(
            _prompts(model, [4], seed=5)[0], max_new_tokens=4
        )
        assert wait_until(req.done.is_set, timeout=120)
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        c.request("GET", "/debug/flight")
        resp = c.getresponse()
        assert resp.status == 200
        body = json.loads(resp.read())
        c.close()
    finally:
        httpd.shutdown()
        server.close(drain=False)
    assert body["entries"], "live engine had an empty flight ring"
    assert body["flight_dumps"] >= 1
    dumps = sink.of("flight_dump")
    assert any(r["reason"] == "debug_endpoint" for r in dumps)


# =====================================================================
# SLO burn rates: window math under an injected clock
# =====================================================================


def test_burn_rate_formula():
    assert burn_rate(100, 100, 0.99) == 0.0
    assert burn_rate(99, 100, 0.99) == pytest.approx(1.0)
    assert burn_rate(90, 100, 0.99) == pytest.approx(10.0)
    # an empty window burns nothing (no traffic, no budget consumed)
    assert burn_rate(0, 0, 0.99) == 0.0


def test_burn_rate_monitor_windows_prune_and_throttle():
    clock = FakeClock(1000.0)
    reg, sink = _registry()
    mon = BurnRateMonitor(
        SloConfig(windows_s=(60.0, 600.0), deadline_objective=0.99,
                  availability_objective=0.999, emit_interval_s=5.0),
        tiers=("interactive", "batch"), registry=reg, now_fn=clock,
    )
    for _ in range(10):
        mon.observe("interactive", available=True, deadline_met=True)
    # one availability failure with NO deadline: it must not touch the
    # deadline ratio
    mon.observe("interactive", available=False, deadline_met=None)

    rates = mon.burn_rates()["interactive"]
    fast = rates["60s"]
    assert fast["requests"] == 11 and fast["deadline_requests"] == 10
    assert fast["deadline_met"] == 1.0 and fast["deadline_burn"] == 0.0
    assert fast["availability"] == pytest.approx(10 / 11)
    assert fast["availability_burn"] == pytest.approx(
        (1 / 11) / 0.001
    )
    assert mon.max_burn() == pytest.approx((1 / 11) / 0.001)
    # the untouched tier reads zero, not missing
    assert mon.burn_rates()["batch"]["60s"]["requests"] == 0
    assert mon.burn_rates()["batch"]["60s"]["availability_burn"] == 0.0

    # past the fast window: the failure ages out of 60s but still burns
    # the 600s budget
    clock.t += 120.0
    rates = mon.burn_rates()["interactive"]
    assert rates["60s"]["requests"] == 0
    assert rates["60s"]["availability_burn"] == 0.0
    assert rates["600s"]["requests"] == 11
    assert rates["600s"]["availability_burn"] > 0.0

    # past the longest window: everything pruned, all burns zero
    clock.t += 600.0
    rates = mon.burn_rates()["interactive"]
    assert rates["600s"]["requests"] == 0
    assert rates["600s"]["availability"] is None
    assert mon.max_burn() == 0.0

    # emission throttle: the 11 rapid observes above emitted exactly once
    # (queries never emit); the first observe past emit_interval_s does
    assert len(sink.of("slo_burn")) == 1
    mon.observe("interactive", available=True)
    burns = sink.of("slo_burn")
    assert len(burns) == 2
    assert burns[-1]["windows_s"] == [60.0, 600.0]
    assert "interactive" in burns[-1]["tiers"]
    assert reg.snapshot()["gauges"]["slo/max_burn"] == burns[-1]["max_burn"]

    # unknown tiers fold into the first tier instead of KeyError-ing the
    # serve path
    mon.observe("mystery", available=True)
    assert mon.stats()["slo_observed"] == 13


def test_slo_coupling_is_default_off_and_opt_in():
    """The burn-rate monitor is PLUMBED into the brownout ladder and the
    autoscaler but acts only when slo_burn_high > 0 — default-off keeps
    pre-obs policy (and the storm bench) byte-identical."""
    import types

    from pytorch_distributed_training_tpu.serve.autoscale import (
        Autoscaler,
        AutoscaleConfig,
    )
    from pytorch_distributed_training_tpu.serve.queue import (
        BrownoutController,
    )

    burning = types.SimpleNamespace(max_burn=lambda now=None: 50.0)

    # ---- brownout: default off -> burning monitor moves nothing
    clock = FakeClock()
    reg, _sink = _registry()
    br = BrownoutController(
        high_watermark=0.8, low_watermark=0.3,
        escalate_hold_s=0.5, deescalate_hold_s=0.5,
        now_fn=clock, registry=reg, slo_monitor=burning,
    )
    for _ in range(5):
        br.observe(0.0)
        clock.t += 1.0
    assert br.level == 0

    # ---- brownout: opted in -> burn escalates despite an empty queue
    br2 = BrownoutController(
        high_watermark=0.8, low_watermark=0.3,
        escalate_hold_s=0.5, deescalate_hold_s=0.5,
        now_fn=clock, registry=reg, slo_monitor=burning, slo_burn_high=2.0,
    )
    br2.observe(0.0)
    clock.t += 0.6
    br2.observe(0.0)
    assert br2.level == 1
    # burn subsides -> the ladder comes back down on queue pressure alone
    burning.max_burn = lambda now=None: 0.0
    br2.observe(0.0)
    clock.t += 0.6
    br2.observe(0.0)
    assert br2.level == 0

    # ---- autoscaler: the burn signal is visible either way, acted on
    # only when opted in
    class View:
        def __init__(self, name):
            self.name = name
            self.breaker = types.SimpleNamespace(state="closed")
            self.health = {"queue_depth": 0.0, "page_occupancy": 0.0}

        def available(self):
            return True

    class FakeFleet:
        def __init__(self):
            self.router = types.SimpleNamespace(replicas=[View("r0")])
            self.replicas = [types.SimpleNamespace(name="r0", state="up")]
            self.ups = 0

        def scale_up(self):
            self.ups += 1
            v = View(f"r{self.ups}")
            self.router.replicas.append(v)
            proc = types.SimpleNamespace(name=v.name, state="up")
            self.replicas.append(proc)
            return proc

        def retire_replica(self):
            return None

    hot = types.SimpleNamespace(max_burn=lambda now=None: 10.0)
    clock2 = FakeClock()

    off = Autoscaler(
        FakeFleet(), AutoscaleConfig(up_hold_s=1.0, up_cooldown_s=5.0),
        now_fn=clock2, registry=reg, slo_monitor=hot,
    )
    assert off.signals()["slo_burn"] == 10.0   # visible in telemetry
    for _ in range(5):
        assert off.step() is None              # ...but never acted on
        clock2.t += 1.0

    on_fleet = FakeFleet()
    on = Autoscaler(
        on_fleet,
        AutoscaleConfig(up_hold_s=1.0, up_cooldown_s=5.0,
                        slo_burn_high=3.0),
        now_fn=clock2, registry=reg, slo_monitor=hot,
    )
    assert on.step() is None                   # onset: hold starts
    clock2.t += 1.1
    assert on.step() == "up"                   # burn alone scaled the pool
    assert on_fleet.ups == 1


# =====================================================================
# renderers: trace_view waterfall golden + summarize sections
# =====================================================================


def _synthetic_stream():
    """One complete trace, one orphan trace, one slo_burn, one
    flight_dump — deterministic via injected tracer clocks."""
    reg, sink = _registry()
    tr = Tracer(registry=reg, component="engine",
                now_fn=lambda: 100.0, wall_fn=lambda: 1000.0)
    serve = tr.begin("req-g", "serve", t0=0.0,
                     attrs={"tier": "interactive"})
    q = tr.begin("req-g", "queue", parent=serve.span, t0=0.0)
    tr.end(q, t1=0.2, attrs={"tier": "interactive"})
    p = tr.begin("req-g", "prefill", parent=serve.span, t0=0.2)
    tr.end(p, t1=0.5, attrs={"bucket": 16})
    d = tr.begin("req-g", "decode", parent=serve.span, t0=0.5)
    tr.end(d, t1=1.0, attrs={"tokens": 8})
    tr.end(serve, t1=1.0)
    # an orphan: its parent span id never appears in the stream (an
    # unmerged replica file, or a dropped root)
    lost = tr.begin("req-lost", "serve", parent="router-gone-1", t0=0.0)
    tr.end(lost, t1=0.3)

    clock = FakeClock(1000.0)
    mon = BurnRateMonitor(
        SloConfig(windows_s=(60.0, 600.0)), tiers=("interactive",),
        registry=reg, now_fn=clock,
    )
    for ok in (True, True, True, False):
        mon.observe("interactive", available=ok, deadline_met=ok)
    mon.emit_now()   # the throttled observes above emitted only once

    fr = FlightRecorder(8, component="engine", registry=reg)
    for i in range(3):
        fr.record(tick=i, busy_tick=i)
    fr.dump("unit_test")
    return sink.records


GOLDEN_WATERFALL = """\
trace req-g: 4 span(s), complete, phases ok (1000.0ms of 1000.0ms serve)
  serve                    engine       +     0.0ms    1000.0ms  tier=interactive
    queue                  engine       +     0.0ms     200.0ms  tier=interactive
    prefill                engine       +   200.0ms     300.0ms  bucket=16
    decode                 engine       +   500.0ms     500.0ms  tokens=8"""


def test_trace_view_waterfall_golden():
    tv = _load_script("trace_view")
    records = _synthetic_stream()
    assert tv.render_waterfall(records, "req-g") == GOLDEN_WATERFALL

    # the orphan trace renders its spans under the orphans heading and is
    # flagged INCOMPLETE instead of silently vanishing
    lost = tv.render_waterfall(records, "req-lost")
    assert "INCOMPLETE" in lost.splitlines()[0]
    assert "orphans (parent span not in merged streams):" in lost
    assert "parent=router-gone-1" in lost

    assert "no spans found" in tv.render_waterfall(records, "nope")

    listing = tv.render_trace_list(records)
    assert "req-g" in listing and "req-lost" in listing
    assert "complete" in listing and "INCOMPLETE" in listing


def test_trace_view_timeline_orders_fleet_events():
    tv = _load_script("trace_view")
    records = _synthetic_stream() + [
        {"record": "fleet_scale", "ts": 10.0, "action": "up",
         "replica": "r1", "size": 2},
        {"record": "brownout_transition", "ts": 12.5, "from": 0, "to": 1,
         "level": 1},
    ]
    out = tv.render_timeline(records)
    lines = out.splitlines()
    assert lines[0] == "fleet timeline:"
    # sink-timestamp order, relative offsets from the first event
    scale = next(l for l in lines if "fleet_scale" in l)
    brown = next(l for l in lines if "brownout_transition" in l)
    assert "action=up replica=r1 size=2" in scale
    assert "from=0 to=1 level=1" in brown
    assert lines.index(scale) < lines.index(brown)
    # slo_burn + flight_dump from the synthetic stream are events too
    assert any("slo_burn" in l for l in lines)
    assert any("flight_dump" in l for l in lines)
    assert any(l.startswith("traces: 2 (1 complete)") for l in lines)


def test_trace_view_load_dir_merges_and_skips_torn_lines(tmp_path):
    tv = _load_script("trace_view")
    records = _synthetic_stream()
    split = len(records) // 2
    (tmp_path / "replica-0").mkdir()
    with open(tmp_path / "metrics.jsonl", "w") as f:
        for r in records[:split]:
            f.write(json.dumps(r) + "\n")
        f.write('{"record": "span", "torn')   # crashed writer's last line
    with open(tmp_path / "replica-0" / "metrics.jsonl", "w") as f:
        for r in records[split:]:
            f.write(json.dumps(r) + "\n")
    merged = tv.load_dir(str(tmp_path))
    assert len(merged) == len(records)        # torn line skipped, rest kept
    assert trace_summary(
        spans_by_trace(merged)["req-g"]
    )["complete"] is True
    with pytest.raises(FileNotFoundError):
        tv.load_dir(str(tmp_path / "replica-0" / "nothing-here"))


def test_summarize_metrics_obs_sections(tmp_path):
    stream = tmp_path / "metrics.jsonl"
    with open(stream, "w") as f:
        for r in _synthetic_stream():
            f.write(json.dumps(r) + "\n")

    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream),
         "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    data = json.loads(proc.stdout)

    spans = data["spans"]
    assert spans["traces"] == 2 and spans["complete_traces"] == 1
    assert spans["incomplete_traces"] == 1 and spans["orphan_spans"] == 1
    assert spans["coverage"] == 0.5
    assert spans["components"] == ["engine"]
    tiers = spans["tiers"]["interactive"]
    assert set(tiers) == set(REQUEST_PHASES)
    assert tiers["queue"]["p50"] == pytest.approx(0.2)
    assert tiers["decode"]["p95"] == pytest.approx(0.5)

    slo = data["slo"]
    assert slo["emissions"] >= 1
    assert slo["deadline_objective"] == 0.99
    assert slo["max_burn"] == slo["peak_burn"] > 1.0
    fast = slo["tiers"]["interactive"]["60s"]
    assert fast["requests"] == 4 and fast["deadline_met"] == 0.75

    flight = data["flight"]
    assert flight["dumps"] == 1
    assert flight["by_reason"] == {"unit_test": 1}
    assert flight["detail"][0]["last_tick"] == 2

    # the text table carries all three sections
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "spans:" in proc.stdout and "[INCOMPLETE]" in proc.stdout
    assert "slo:" in proc.stdout and "[BURNING]" in proc.stdout
    assert "flight-dumps: 1 (unit_test=1)" in proc.stdout


# =====================================================================
# subprocess drill: trace context across the process boundary + SIGTERM
# =====================================================================

REPLICA_ARGS = (
    "--model", "gpt2-tiny", "--num-slots", "2",
    "--prompt-buckets", "16,32", "--max-new-tokens-cap", "64",
    "--queue-depth", "16", "--stall-timeout-s", "10",
)


def test_fleet_trace_merges_across_processes_and_sigterm_dumps(tmp_path):
    """End-to-end X-Request-Id contract with a REAL replica: the router's
    request/attempt spans (coordinator stream) and the replica's
    serve/queue/prefill/decode spans (its own metrics dir) merge into ONE
    complete tree keyed by the client's request id, with the serve span
    parented under the router's attempt via the X-Parent-Span header.
    Then SIGTERM: the drain path dumps the replica's flight ring to the
    same on-disk stream."""
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )
    from pytorch_distributed_training_tpu.serve.router import (
        make_router_http_server,
    )

    tv = _load_script("trace_view")
    reg, sink = _registry()
    fleet = ServeFleet(
        FleetConfig(
            num_replicas=1,
            replica_args=REPLICA_ARGS,
            replica_extra_args={0: (
                "--metrics-dir", str(tmp_path / "replica-0"),
                "--replica-name", "replica-0",
            )},
            max_restarts=1,
            backoff_s=0.2,
            drain_timeout_s=20.0,
        ),
        RouterConfig(
            health_interval_s=0.05, health_timeout_s=1.0,
            breaker_threshold=3, breaker_cooldown_s=0.5,
            retry_backoff_s=0.02, retry_backoff_max_s=0.1,
            ttfb_timeout_s=60.0,
        ),
        registry=reg,
    ).start()
    httpd = None
    rid = "obs-e2e-1"
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        httpd = make_router_http_server(fleet.router)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": "trace me", "max_new_tokens": 6}),
            headers={"X-Request-Id": rid},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        conn.close()
        assert events[-1]["event"] == "done", events[-3:]

        fleet.replica(0).sigterm()
        assert wait_until(
            lambda: len(sink.of("replica_exit")) >= 1, timeout=60
        )
        exits = sink.of("replica_exit")
        assert exits[0]["graceful"] is True and exits[0]["rc"] == 75
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.stop(drain=False)

    # merge the coordinator's in-memory stream with the replica's on-disk
    # one — exactly what trace_view does for a fleet metrics dir
    with open(tmp_path / "metrics.jsonl", "w") as f:
        for r in sink.records:
            f.write(json.dumps(r) + "\n")
    merged = tv.load_dir(str(tmp_path))

    traces = spans_by_trace(merged)
    assert rid in traces, sorted(traces)
    spans = {s["name"]: s for s in traces[rid]}
    assert {"request", "attempt", "serve", "queue", "prefill",
            "decode"} <= set(spans)
    summary = trace_summary(traces[rid])
    assert summary["complete"] is True, summary
    assert summary["phase_sum_ok"] is True, summary
    # the cross-process link: the replica's serve span hangs under the
    # router-generated attempt span id it got over HTTP
    assert spans["serve"]["parent"] == spans["attempt"]["span"]
    assert spans["serve"]["component"] == "replica-0"
    assert spans["request"]["component"] == "router"

    waterfall = tv.render_waterfall(merged, rid)
    assert "complete" in waterfall.splitlines()[0]

    # the preemption black box: SIGTERM drain dumped the replica's ring
    # into its own stream before exit 75
    replica_records = tv.load_file(
        str(tmp_path / "replica-0" / "metrics.jsonl")
    )
    dumps = [
        r for r in replica_records
        if r.get("record") == "flight_dump"
        and r.get("reason") == "sigterm_drain"
    ]
    assert dumps, [r.get("record") for r in replica_records][-20:]
    assert dumps[0]["entries"], "drain dump carried an empty ring"
