"""Serving-fleet resilience tests (serve/router.py + serve/fleet.py).

Three tiers, all CPU and tier-1:

- pure state-machine tests (circuit breaker, fault-spec parsing, fault
  routing) with injected clocks — no sockets, no sleeps;
- stub-replica tests: the router against tiny in-test HTTP servers whose
  failure behavior is a switch (refused, pre-stream reset, mid-stream
  death, slow first byte, unhealthy healthz) — every routing policy is
  exercised without paying a subprocess boot;
- subprocess chaos drills: REAL replica processes (cli/serve_lm.py,
  gpt2-tiny, random weights) under the fleet supervisor, with
  ``PDT_TPU_FAULT=replica_crash`` killing one mid-load and SIGTERM
  driving the drain/exit-75 contract end-to-end.

The acceptance bar throughout: every submitted request either streams to
completion or fails with an EXPLICIT retryable error — zero hung waiters.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_training_tpu.serve.router import (
    CircuitBreaker,
    Router,
    RouterConfig,
)
from pytorch_distributed_training_tpu.serve.server import wait_until

pytestmark = [pytest.mark.serve, pytest.mark.chaos]


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        with self._lock:
            self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        with self._lock:
            return [r for r in self.records if r.get("record") == kind]


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


# =====================================================================
# state machines (no sockets)
# =====================================================================


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_circuit_breaker_opens_half_opens_closes():
    clock = FakeClock()
    transitions = []
    br = CircuitBreaker(
        threshold=3, cooldown_s=2.0, now_fn=clock,
        on_transition=lambda a, b: transitions.append((a, b)),
    )
    assert br.state == br.CLOSED and br.allow_probe()
    br.record_failure()
    br.record_failure()
    assert br.state == br.CLOSED    # under threshold: still closed
    br.record_failure()
    assert br.state == br.OPEN      # 3 consecutive failures -> open
    assert not br.allow_probe()     # cooldown not yet over
    assert br.reopen_in() == pytest.approx(2.0)
    clock.t += 2.5
    assert br.allow_probe()         # cooldown over -> half-open probe
    assert br.state == br.HALF_OPEN
    br.record_success()
    assert br.state == br.CLOSED and br.failures == 0
    assert transitions == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "closed"),
    ]


def test_circuit_breaker_half_open_failure_reopens():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown_s=1.0, now_fn=clock)
    br.record_failure()
    br.record_failure()
    assert br.state == br.OPEN
    clock.t += 1.1
    assert br.allow_probe() and br.state == br.HALF_OPEN
    br.record_failure()             # probe failed -> straight back to open
    assert br.state == br.OPEN
    assert not br.allow_probe()     # and the cooldown restarted
    clock.t += 1.1
    assert br.allow_probe()
    # a success after an intervening failure history still closes cleanly
    br.record_success()
    assert br.state == br.CLOSED


def test_serve_fault_spec_parsing():
    from pytorch_distributed_training_tpu.faults.inject import FaultPlan

    plan = FaultPlan.parse(
        "replica_crash:5,replica_hang:3:0.5,replica_slow:2:4x"
    )
    kinds = [(s.kind, s.step, s.factor) for s in plan.specs]
    assert kinds == [
        ("replica_crash", 5, 1.0),
        ("replica_hang", 3, 0.5),
        ("replica_slow", 2, 4.0),
    ]
    # hang duration defaults when omitted
    assert FaultPlan.parse("replica_hang:3").specs[0].factor == 2.0
    for bad in (
        "replica_crash:0",          # non-positive tick
        "replica_crash:2:9",        # crash takes a bare tick
        "replica_slow:2",           # slow needs a factor
        "replica_slow:2:0.5x",      # factor < 1
        "replica_hang:1:2:3",       # too many parts
    ):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)


def test_split_fault_specs_routes_by_rank():
    from pytorch_distributed_training_tpu.serve.fleet import split_fault_specs

    routed = split_fault_specs(
        "replica_crash:5@1,replica_slow:2:4x,crash_at_step:3,"
        "replica_hang:1:0.2@1"
    )
    # serve-scoped specs land on their @rank replica (suffix stripped);
    # train-scoped specs never reach a replica env
    assert routed == {
        1: "replica_crash:5,replica_hang:1:0.2",
        0: "replica_slow:2:4x",
    }
    assert split_fault_specs(None) == {}
    assert split_fault_specs("crash_at_step:3") == {}


# =====================================================================
# stub replicas: routing policy without subprocess boots
# =====================================================================


class StubReplica:
    """A minimal replica-shaped HTTP server whose behavior is a switch.

    ``mode``: "ok" (stream ``tokens`` then done), "reset" (close before
    any byte), "mid_stream" (stream 2 tokens then close, no done),
    "busy" (429 + Retry-After), "slow" (sleep ``ttfb_s`` then stream).
    ``health``: "ready" | "draining" | "unhealthy" | "dead" (refuse).
    """

    def __init__(self, *, mode="ok", health="ready", tokens=3,
                 ttfb_s=0.0, queue_depth=0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        stub = self
        self.mode = mode
        self.health = health
        self.tokens = tokens
        self.ttfb_s = ttfb_s
        self.queue_depth = queue_depth
        self.generate_hits = 0
        self.health_hits = 0

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):
                pass

            def _json(self, code, obj, headers=None):
                body = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                stub.health_hits += 1
                state = stub.health
                payload = {
                    "state": state,
                    "queue_depth": stub.queue_depth,
                    "slot_occupancy": 0.0,
                    "num_slots": 1,
                }
                self._json(200 if state == "ready" else 503, payload)

            def do_POST(self):
                stub.generate_hits += 1
                rid = self.headers.get("X-Request-Id", "?")
                if stub.mode == "reset":
                    self.wfile.close()      # die before any byte
                    return
                if stub.mode == "busy":
                    self._json(429, {"error": "full"},
                               headers={"Retry-After": 1})
                    return
                if stub.mode == "slow":
                    time.sleep(stub.ttfb_s)
                self.send_response(200)
                self.end_headers()
                n = 2 if stub.mode == "mid_stream" else stub.tokens
                for i in range(n):
                    self.wfile.write((json.dumps({
                        "id": rid, "event": "token", "token_id": i,
                    }) + "\n").encode())
                    self.wfile.flush()
                if stub.mode == "mid_stream":
                    self.wfile.close()      # EOF with no done event
                    return
                self.wfile.write((json.dumps({
                    "id": rid, "event": "done", "status": "done",
                    "new_tokens": n,
                }) + "\n").encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        if health != "dead":
            self._thread.start()

    def close(self):
        if self._thread.is_alive():
            self.httpd.shutdown()


def _make_router(stubs, registry=None, **cfg_kw):
    cfg = RouterConfig(**{
        "health_interval_s": 0.03,
        "health_timeout_s": 0.5,
        "breaker_threshold": 3,
        "breaker_cooldown_s": 0.25,
        "retry_backoff_s": 0.01,
        "retry_backoff_max_s": 0.05,
        "ttfb_timeout_s": 5.0,
        **cfg_kw,
    })
    router = Router(
        [(f"s{i}", "127.0.0.1", s.port) for i, s in enumerate(stubs)],
        cfg, registry=registry,
    )
    return router


def _collect_lines():
    lines = []

    def write(b):
        lines.append(json.loads(b))

    return lines, write


def _wait_in_rotation(router, n, timeout=5.0):
    assert wait_until(
        lambda: router.available_count() >= n, timeout=timeout
    ), router.stats()


def test_router_all_replicas_down_returns_503_retry_after():
    """Nothing listening on either endpoint: breakers open fast and a
    request fails FAST with 503 + Retry-After — never a hang."""
    from pytorch_distributed_training_tpu.serve.fleet import find_free_port
    from pytorch_distributed_training_tpu.serve.router import (
        make_router_http_server,
    )

    reg, sink = _registry()
    router = Router(
        [("a", "127.0.0.1", find_free_port()),
         ("b", "127.0.0.1", find_free_port())],
        RouterConfig(health_interval_s=0.03, health_timeout_s=0.3,
                     breaker_threshold=2, breaker_cooldown_s=30.0),
        registry=reg,
    ).start()
    httpd = make_router_http_server(router)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert wait_until(
            lambda: all(
                r.breaker.state == "open" for r in router.replicas
            ),
            timeout=10,
        )
        t0 = time.monotonic()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt": "hi"}))
        resp = conn.getresponse()
        elapsed = time.monotonic() - t0
        assert resp.status == 503
        assert int(resp.getheader("Retry-After")) >= 1
        assert resp.getheader("X-Request-Id")
        assert elapsed < 5.0        # fail-fast, not fail-by-timeout
        conn.close()
        # the router's own healthz advertises the dead pool the same way
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 503
        assert resp.getheader("Retry-After")
        conn.close()
        assert sink.of("router_request")[-1]["status"] == "rejected"
    finally:
        httpd.shutdown()
        router.close()


def test_router_failover_before_first_byte():
    """Pre-stream replica death is idempotent: the router retries the SAME
    request on the other replica and the client sees one clean stream."""
    reg, sink = _registry()
    a = StubReplica(mode="reset", queue_depth=0)     # dies pre-byte, low load
    b = StubReplica(mode="ok", tokens=3, queue_depth=5)  # healthy, loaded
    router = _make_router([a, b], registry=reg).start()
    try:
        _wait_in_rotation(router, 2)
        lines, write = _collect_lines()
        out = router.route_generate(
            json.dumps({"prompt": "x"}).encode(), "req-1", write
        )
        assert out["status"] == "ok"
        assert out["replica"] == "s1"
        assert out["attempts"] == 2         # s0 (least loaded) died first
        assert lines[-1]["event"] == "done"
        assert len([l for l in lines if l["event"] == "token"]) == 3
        assert router.failovers == 1
        fo = sink.of("router_failover")
        assert len(fo) == 1 and fo[0]["to"] == "s1"
        req = sink.of("router_request")[-1]
        assert req["status"] == "ok" and req["attempts"] == 2
    finally:
        router.close()
        a.close()
        b.close()


def test_router_mid_stream_failure_is_explicit_retryable_error():
    """Once bytes streamed, no silent retry and no hang: the client gets
    its partial tokens plus a terminal error event marked retryable."""
    reg, sink = _registry()
    a = StubReplica(mode="mid_stream", queue_depth=0)
    b = StubReplica(mode="ok", queue_depth=5)
    router = _make_router([a, b], registry=reg).start()
    try:
        _wait_in_rotation(router, 2)
        lines, write = _collect_lines()
        out = router.route_generate(
            json.dumps({"prompt": "x"}).encode(), "req-2", write
        )
        assert out["status"] == "error_midstream"
        assert out["attempts"] == 1         # never duplicated downstream
        assert lines[-1]["event"] == "error"
        assert lines[-1]["retryable"] is True
        assert [l["event"] for l in lines[:-1]] == ["token", "token"]
        assert b.generate_hits == 0         # the stream was NOT re-sent
        assert sink.of("router_request")[-1]["status"] == "error_midstream"
    finally:
        router.close()
        a.close()
        b.close()


def test_router_retries_busy_replica_without_breaker_harm():
    """429 from a loaded replica reroutes the request but does NOT count
    against the breaker — busy is healthy."""
    reg, _sink = _registry()
    a = StubReplica(mode="busy", queue_depth=0)
    b = StubReplica(mode="ok", queue_depth=5)
    router = _make_router([a, b], registry=reg).start()
    try:
        _wait_in_rotation(router, 2)
        lines, write = _collect_lines()
        out = router.route_generate(
            json.dumps({"prompt": "x"}).encode(), "req-3", write
        )
        assert out["status"] == "ok" and out["replica"] == "s1"
        assert lines[-1]["event"] == "done"
        assert router.replicas[0].breaker.state == "closed"
    finally:
        router.close()
        a.close()
        b.close()


def test_router_hedges_slow_ttfb():
    """No first byte within hedge_s: a second replica races the first and
    the client streams from whichever answers first."""
    reg, sink = _registry()
    a = StubReplica(mode="slow", ttfb_s=3.0, queue_depth=0)
    b = StubReplica(mode="ok", tokens=2, queue_depth=5)
    router = _make_router([a, b], registry=reg, hedge_s=0.1).start()
    try:
        _wait_in_rotation(router, 2)
        lines, write = _collect_lines()
        t0 = time.monotonic()
        out = router.route_generate(
            json.dumps({"prompt": "x"}).encode(), "req-4", write
        )
        elapsed = time.monotonic() - t0
        assert out["status"] == "ok"
        assert out["hedged"] is True
        assert out["replica"] == "s1"       # the hedge won
        assert lines[-1]["event"] == "done"
        assert elapsed < 2.5                # did not wait out the slow TTFB
        assert router.hedges == 1
        hedge = sink.of("router_hedge")
        assert len(hedge) == 1
        assert hedge[0]["primary"] == "s0" and hedge[0]["hedge"] == "s1"
    finally:
        router.close()
        a.close()
        b.close()


def test_breaker_trips_on_unhealthy_and_recovers_via_half_open():
    """An unhealthy replica leaves rotation after `threshold` consecutive
    bad polls; when it turns healthy again, the half-open probe puts it
    back — the full trip/recover cycle through REAL health polling."""
    reg, sink = _registry()
    a = StubReplica(mode="ok", health="ready")
    router = _make_router([a], breaker_cooldown_s=0.2, registry=reg).start()
    try:
        _wait_in_rotation(router, 1)
        a.health = "unhealthy"
        assert wait_until(
            lambda: router.replicas[0].breaker.state == "open", timeout=10
        )
        assert router.pick() is None        # out of rotation
        a.health = "ready"
        assert wait_until(
            lambda: router.replicas[0].breaker.state == "closed", timeout=10
        )
        assert router.pick() is not None    # recovered
        seq = [(r["from"], r["to"]) for r in sink.of("router_breaker")]
        assert ("closed", "open") in seq
        assert ("open", "half_open") in seq
        assert ("half_open", "closed") in seq
    finally:
        router.close()
        a.close()


def test_router_drains_draining_replica_out_of_rotation():
    """A replica advertising 'draining' leaves rotation at the next poll
    without tripping its breaker — it is healthy, just leaving."""
    reg, sink = _registry()
    a = StubReplica(mode="ok", health="ready")
    router = _make_router([a], registry=reg).start()
    try:
        _wait_in_rotation(router, 1)
        a.health = "draining"
        assert wait_until(lambda: router.replicas[0].draining, timeout=10)
        assert router.pick() is None
        assert router.replicas[0].breaker.state == "closed"
        states = sink.of("router_replica_state")
        assert states and states[-1]["draining"] is True
    finally:
        router.close()
        a.close()


def test_pick_least_loaded_with_round_robin_ties():
    a = StubReplica(queue_depth=0)
    b = StubReplica(queue_depth=4)
    c = StubReplica(queue_depth=0)
    router = _make_router([a, b, c])
    for i, r in enumerate(router.replicas):     # hand-feed health samples
        r.health = {"queue_depth": [0, 4, 0][i], "slot_occupancy": 0.0,
                    "num_slots": 1}
        r.last_ready_t = time.monotonic()
    picks = {router.pick().name for _ in range(8)}
    assert picks == {"s0", "s2"}        # never the loaded replica...
    assert router.pick(exclude=frozenset({"s0", "s2"})).name == "s1"  # ...unless excluded
    for s in (a, b, c):
        s.close()


# =====================================================================
# replica-side health states (in-process InferenceServer)
# =====================================================================


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _server(lm, reg=None, **kw):
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )

    model, params = lm
    kw.setdefault("queue_depth", 4)
    return InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(8,), max_new_tokens=64),
        registry=reg, **kw,
    )


def _prompt(model, n=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, model.config.vocab_size, n).astype(np.int32)


def test_healthz_reports_states_and_load(lm):
    """/healthz: ready with load fields; 503 'draining' once shutdown
    begins; 503 'unhealthy' when the serve loop dies."""
    from pytorch_distributed_training_tpu.data.bpe import ByteTokenizer
    from pytorch_distributed_training_tpu.serve import make_http_server

    server = _server(lm)
    httpd = make_http_server(server, ByteTokenizer())
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def healthz():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        retry = resp.getheader("Retry-After")
        conn.close()
        return resp.status, payload, retry

    try:
        status, payload, _ = healthz()
        assert status == 200 and payload["state"] == "ready"
        for key in ("queue_depth", "slot_occupancy", "num_slots"):
            assert key in payload

        # draining: visible on /healthz as a 503 the moment the queue
        # refuses admissions — external LBs act on the status code
        server.queue.close()
        status, payload, retry = healthz()
        assert status == 503 and payload["state"] == "draining"
        assert retry is not None
    finally:
        httpd.shutdown()
        server.close(drain=False)

    # a dead serve loop is 'unhealthy', not 'draining' (different fix:
    # replace the replica, don't wait for it)
    server2 = _server(lm)

    def boom():
        raise RuntimeError("injected tick failure")

    server2.engine.tick = boom
    server2.start()
    assert wait_until(lambda: server2.queue.closed, timeout=30)
    assert server2.health()["state"] == "unhealthy"
    server2.close(drain=False)


def test_http_request_id_propagates_to_telemetry_and_events(lm):
    """X-Request-Id flows header -> queue -> engine -> telemetry record ->
    response header + every streamed event; 429 carries Retry-After."""
    from pytorch_distributed_training_tpu.data.bpe import ByteTokenizer
    from pytorch_distributed_training_tpu.serve import make_http_server

    reg, sink = _registry()
    server = _server(lm, reg=reg).start()
    httpd = make_http_server(server, ByteTokenizer())
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": "hello", "max_new_tokens": 3}),
            headers={"X-Request-Id": "trace-me-123"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("X-Request-Id") == "trace-me-123"
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        assert all(e["id"] == "trace-me-123" for e in events)
        assert events[-1]["event"] == "done"
        conn.close()
        recs = sink.of("serve_request")
        assert len(recs) == 1 and recs[0]["id"] == "trace-me-123"

        # without the header (or a body id) the server generates one
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": "hi", "max_new_tokens": 2}),
        )
        resp = conn.getresponse()
        rid = resp.getheader("X-Request-Id")
        assert rid
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        assert all(e["id"] == rid for e in events)
        conn.close()
    finally:
        httpd.shutdown()
        server.close(drain=False)

    # backpressure: 429 + Retry-After (loop stopped so fullness is stable)
    server3 = _server(lm, queue_depth=1)
    httpd3 = make_http_server(server3, ByteTokenizer())
    port3 = httpd3.server_address[1]
    threading.Thread(target=httpd3.serve_forever, daemon=True).start()
    try:
        model, _params = lm
        server3.submit(_prompt(model), max_new_tokens=2)    # fills depth 1
        conn = http.client.HTTPConnection("127.0.0.1", port3, timeout=10)
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt": "hi"}))
        resp = conn.getresponse()
        assert resp.status == 429
        assert resp.getheader("Retry-After")
        assert resp.getheader("X-Request-Id")
        conn.close()
    finally:
        httpd3.shutdown()
        server3.close(drain=False)


def test_expired_telemetry_split_queued_vs_running(lm):
    """Deadline expiries are split by phase: queued (overload) vs running
    (stuck/slow replica) — counters and per-request records."""
    model, _params = lm
    reg, sink = _registry()
    server = _server(lm, reg=reg)

    # queued expiry: deadline passes before any tick admits it
    q = server.submit(_prompt(model, seed=1), max_new_tokens=4,
                      deadline_s=0.01)
    time.sleep(0.05)
    server.engine.tick()
    assert q.done.is_set() and q.status == "expired"

    # running expiry: admit with a generous deadline, then shrink it
    r = server.submit(_prompt(model, seed=2), max_new_tokens=64,
                      deadline_s=60.0)
    while r.admit_t is None:
        server.engine.tick()
    r.deadline_s = 1e-4
    while not r.done.is_set():
        server.engine.tick()
    assert r.status == "expired" and len(r.tokens) > 0

    recs = sink.of("serve_expired")
    assert [x["phase"] for x in recs] == ["queued", "running"]
    assert recs[0]["id"] == q.id and recs[1]["id"] == r.id
    counters = reg.snapshot()["counters"]
    assert counters["serve/expired_queued"] == 1
    assert counters["serve/expired_running"] == 1
    assert counters["serve/expired"] == 2
    server.close(drain=False)


def test_replica_hang_injection_goes_unhealthy_then_recovers(lm):
    """PDT_TPU_FAULT=replica_hang freezes the serve loop at an exact busy
    tick: /healthz flips to 'unhealthy' while the heartbeat is stale and
    back to 'ready' when the loop resumes — the signal a router's breaker
    trips on and recovers from."""
    from pytorch_distributed_training_tpu.faults.inject import (
        FaultPlan,
        set_plan,
    )

    model, _params = lm
    server = _server(lm, stall_timeout_s=0.25).start()
    try:
        # warm: compile prefill+decode OUTSIDE the injected window so the
        # hang tick is the only slow tick (busy ticks 1..3)
        warm = server.submit(_prompt(model, seed=3), max_new_tokens=3)
        assert wait_until(warm.done.is_set, timeout=120)
        assert server.health()["state"] == "ready"

        prev = set_plan(FaultPlan.parse("replica_hang:5:1.0"))
        try:
            req = server.submit(_prompt(model, seed=4), max_new_tokens=8)
            saw_unhealthy = wait_until(
                lambda: server.health()["state"] == "unhealthy", timeout=10
            )
            assert saw_unhealthy    # stale heartbeat detected mid-hang
            assert wait_until(req.done.is_set, timeout=120)
            assert req.status == "done"
            assert wait_until(
                lambda: server.health()["state"] == "ready", timeout=10
            )
        finally:
            set_plan(prev)
    finally:
        server.close(drain=False)


# =====================================================================
# subprocess chaos drills: REAL replicas under the fleet supervisor
# =====================================================================

REPLICA_ARGS = (
    "--model", "gpt2-tiny", "--num-slots", "2",
    "--prompt-buckets", "16,32", "--max-new-tokens-cap", "64",
    "--queue-depth", "16", "--stall-timeout-s", "10",
)


def _fleet(num_replicas, fault_env=None, registry=None, **router_kw):
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )

    return ServeFleet(
        FleetConfig(
            num_replicas=num_replicas,
            replica_args=REPLICA_ARGS,
            fault_env=fault_env or {},
            max_restarts=1,
            backoff_s=0.2,
            drain_timeout_s=20.0,
        ),
        RouterConfig(**{
            "health_interval_s": 0.05,
            "health_timeout_s": 1.0,
            "breaker_threshold": 3,
            "breaker_cooldown_s": 0.5,
            "retry_backoff_s": 0.02,
            "retry_backoff_max_s": 0.1,
            "ttfb_timeout_s": 60.0,
            **router_kw,
        }),
        registry=registry,
    )


def _post_generate(port, prompt, max_new, rid, timeout=120):
    """One closed-loop client request through the router; returns a dict
    classifying the outcome (never raises, never hangs past timeout)."""
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": prompt, "max_new_tokens": max_new}),
            headers={"X-Request-Id": rid},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            retry_after = resp.getheader("Retry-After")
            resp.read()
            conn.close()
            return {"outcome": "rejected", "status": resp.status,
                    "retry_after": retry_after}
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        conn.close()
        last = events[-1] if events else {}
        if last.get("event") == "done":
            return {"outcome": "done", "events": events}
        if last.get("event") == "error" and last.get("retryable"):
            return {"outcome": "retryable_error", "events": events}
        return {"outcome": "bad", "events": events}
    except Exception as e:          # pragma: no cover - drill diagnostics
        return {"outcome": "exception", "error": repr(e)}


def test_fleet_replica_crash_mid_load_fails_over(tmp_path):
    """THE acceptance drill: 2 replicas, PDT_TPU_FAULT=replica_crash kills
    one mid-load. Every request streams to completion or fails with an
    explicit retryable error (zero hung waiters); the router records the
    failover; the supervisor respawns the dead replica (burning a
    restart) and the pool recovers."""
    from pytorch_distributed_training_tpu.serve.router import (
        make_router_http_server,
    )

    reg, sink = _registry()
    fleet = _fleet(
        2, fault_env={0: "replica_crash:6"}, registry=reg
    ).start()
    httpd = None
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        httpd = make_router_http_server(fleet.router)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        results = [None] * 8
        threads = []
        for i in range(8):
            def run(i=i):
                results[i] = _post_generate(
                    port, f"request number {i}", 8, f"drill-{i}"
                )
            t = threading.Thread(target=run, daemon=True)
            threads.append(t)
            t.start()
        for t in threads:
            t.join(180)
        # ZERO hung waiters: every client thread finished and every
        # outcome is terminal-and-explicit
        assert all(t.is_alive() is False for t in threads)
        outcomes = [r["outcome"] for r in results]
        assert all(
            o in ("done", "retryable_error", "rejected") for o in outcomes
        ), results
        assert outcomes.count("done") >= 1      # the survivor kept serving

        # the crash really happened and was recorded as a CRASH (rc != 75)
        crashes = [
            r for r in sink.of("replica_exit") if not r["graceful"]
        ]
        assert crashes and crashes[0]["replica"] == "r0"
        assert crashes[0]["rc"] == 23       # REPLICA_CRASH_EXIT_CODE

        # the router recorded the failover path it took
        counters = reg.snapshot()["counters"]
        failovers = counters.get("router/failovers", 0)
        midstream = counters.get("router/midstream_errors", 0)
        assert failovers + midstream >= 1, counters

        # supervision: r0 respawned, burning a restart from the budget
        assert wait_until(
            lambda: fleet.replica(0).describe()["restarts_used"] >= 1,
            timeout=60,
        )
        assert fleet.wait_ready(timeout=120, min_replicas=2)
        post = _post_generate(port, "after recovery", 4, "drill-post")
        assert post["outcome"] == "done", post
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.stop(drain=False)

    # the drill's stream folds into the summarize_metrics fleet section
    import subprocess
    import sys

    stream = str(tmp_path / "metrics.jsonl")
    with open(stream, "w") as f:
        for r in sink.records:
            f.write(json.dumps(r) + "\n")
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", stream, "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    fleet_summary = json.loads(proc.stdout)["fleet"]
    assert fleet_summary["routed"] >= 8
    assert fleet_summary["failovers"] + fleet_summary["midstream_errors"] >= 1
    assert "r0" in fleet_summary["replicas"]
    assert fleet_summary["replicas"]["r0"]["crashes"] >= 1


def test_fleet_sigterm_drains_in_flight_and_exits_75():
    """The preemption contract, serve-side: SIGTERM to a replica streaming
    a request -> it advertises draining (router pulls it from rotation),
    FINISHES the in-flight stream, exits 75, and the supervisor respawns
    it without counting a crash."""
    from pytorch_distributed_training_tpu.serve.router import (
        make_router_http_server,
    )

    reg, sink = _registry()
    fleet = _fleet(1, registry=reg).start()
    httpd = None
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        httpd = make_router_http_server(fleet.router)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        # incremental client: events append as lines arrive, so the test
        # can SIGTERM the replica while the stream is provably mid-flight
        events = []
        client_done = threading.Event()

        def client():
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=120
                )
                conn.request(
                    "POST", "/generate",
                    body=json.dumps({
                        "prompt": "a long drain drill request",
                        "max_new_tokens": 64,
                    }),
                    headers={"X-Request-Id": "drain-1"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    events.append(json.loads(line))
                conn.close()
            finally:
                client_done.set()

        t = threading.Thread(target=client, daemon=True)
        t.start()
        replica = fleet.replica(0)
        first_pid = replica.proc.pid
        # wait until tokens are genuinely streaming, then preempt
        assert wait_until(lambda: len(events) >= 2, timeout=60), events
        replica.sigterm()

        # the in-flight stream completes (drain finishes, not cancels)
        assert client_done.wait(120)
        done = events[-1]
        assert done["event"] == "done", events[-3:]
        assert done["new_tokens"] == 64 and done["status"] == "done"

        # exit 75, recorded as graceful with a measured drain duration
        assert wait_until(lambda: len(sink.of("replica_exit")) >= 1,
                          timeout=30)
        exits = sink.of("replica_exit")
        assert exits[0]["graceful"] is True and exits[0]["rc"] == 75
        drains = sink.of("replica_drain")
        assert drains and drains[0]["drain_s"] > 0

        # the router saw 'draining' BEFORE the process died
        states = sink.of("router_replica_state")
        assert any(s["draining"] for s in states), states

        # no restart burned; the replica respawns as fresh capacity
        assert wait_until(
            lambda: fleet.replica(0).describe()["alive"]
            and fleet.replica(0).proc.pid != first_pid,
            timeout=90,
        )
        d = fleet.replica(0).describe()
        assert d["restarts_used"] == 0 and d["graceful_exits"] == 1
        assert fleet.wait_ready(timeout=120)
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.stop(drain=False)
