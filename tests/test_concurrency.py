"""Concurrency analysis tests: static thread-safety rules
(analysis/rules/thread_shared, lock_discipline, thread_lifecycle), the
runtime lock registry (analysis/concurrency), the guards-layer
lock-across-device check, the serve donation-audit hook, lint --changed,
the summarize_metrics "locks" section — and THE tier-1 chaos drill: a
2-replica fleet with hotswap polling under PDT_TPU_GUARDS=strict and the
instrumented lock registry live, asserting zero lock-order violations
and a rendering locks section. CPU-only."""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from pytorch_distributed_training_tpu.analysis.concurrency import (
    LockOrderViolation,
    LockRegistry,
    TracedLock,
    held_lock_names,
    lock,
    set_lock_registry,
)
from pytorch_distributed_training_tpu.analysis.lint import lint_source

pytestmark = pytest.mark.concurrency

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src, path="<string>"):
    return [f.rule for f in lint_source(textwrap.dedent(src), path=path)]


# =====================================================================
# static rules: one positive and one negative fixture per rule
# =====================================================================


def test_thread_shared_flags_unlocked_cross_thread_attr():
    src = """
    import threading

    class Loop:
        def __init__(self):
            self.failed = False
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            self.failed = True

        def health(self):
            return self.failed
    """
    assert "thread-shared-mutable" in rules_of(src)


def test_thread_shared_negative_common_lock_and_safe_attrs():
    src = """
    import threading

    class Loop:
        def __init__(self):
            self._lock = threading.Lock()
            self._stop = threading.Event()     # thread-safe by construction
            self.n = 0
            self._t = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while not self._stop.is_set():
                with self._lock:
                    self.n += 1

        def snapshot(self):
            with self._lock:
                return self.n

        def close(self):
            self._stop.set()
    """
    assert rules_of(src) == []


def test_thread_shared_sees_through_private_locked_callee():
    """swap_to -> _locked pattern: every call site holds the lock, so the
    private body is analyzed as locked (no finding)."""
    src = """
    import threading

    class M:
        def __init__(self):
            self._lock = threading.Lock()
            self.state = "idle"
            self._t = threading.Thread(target=self._poll, daemon=True)

        def _poll(self):
            with self._lock:
                self._advance()

        def swap(self):
            with self._lock:
                self._advance()

        def _advance(self):
            self.state = "busy"
    """
    assert rules_of(src) == []


def test_unlocked_rmw_flags_counter_in_threaded_class():
    src = """
    import threading

    class Router:
        def __init__(self):
            self._lock = threading.Lock()
            self.routed = 0

        def route(self):
            self.routed += 1        # handler threads race each other
    """
    assert rules_of(src) == ["unlocked-rmw"]


def test_unlocked_rmw_negative_unthreaded_class_and_mutator_exempt():
    src = """
    import queue

    class Plain:                     # no locks, no threads: not concurrent
        def __init__(self):
            self.n = 0
            self.q = queue.Queue()   # safe attr even in threaded classes

        def bump(self):
            self.n += 1
            self.q.put(1)
    """
    assert rules_of(src) == []


def test_lock_order_cycle_flags_opposite_nestings():
    src = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
    """
    assert "lock-order-cycle" in rules_of(src)


def test_lock_order_negative_consistent_order():
    src = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
    """
    assert rules_of(src) == []


def test_blocking_call_in_lock_flags_wait_and_http():
    src = """
    import threading

    class M:
        def __init__(self):
            self._lock = threading.Lock()
            self._done = threading.Event()

        def bad_wait(self):
            with self._lock:
                self._done.wait()           # unbounded, lock held

        def bad_http(self, conn):
            with self._lock:
                conn.request("GET", "/x")   # I/O under the lock
    """
    found = rules_of(src)
    assert found.count("blocking-call-in-lock") == 2


def test_blocking_call_negative_timeouts_and_condition():
    src = """
    import threading

    class M:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self._done = threading.Event()

        def ok_bounded(self):
            with self._lock:
                self._done.wait(0.5)        # bounded

        def ok_condition(self):
            with self._cond:
                self._cond.wait()           # releases the lock by contract
    """
    assert rules_of(src) == []


def test_non_daemon_thread_flagged_unless_joined_or_daemon():
    bad = """
    import threading

    def go():
        t = threading.Thread(target=print)
        t.start()
    """
    assert rules_of(bad) == ["non-daemon-thread"]
    joined = """
    import threading

    def go():
        t = threading.Thread(target=print)
        t.start()
        t.join(5.0)
    """
    assert rules_of(joined) == []
    daemonized = """
    import threading

    def go():
        threading.Thread(target=print, daemon=True).start()
    """
    assert rules_of(daemonized) == []


def test_unbounded_wait_flagged_only_in_threading_modules():
    src = """
    import threading

    def collect(req):
        req.done.wait()
    """
    assert rules_of(src) == ["unbounded-wait"]
    # same call, no threading import: out of the rule's scope
    src_unscoped = """
    def collect(req):
        req.done.wait()
    """
    assert rules_of(src_unscoped) == []
    # bounded or condition-like receivers pass
    src_ok = """
    import threading

    def collect(req, cond):
        req.done.wait(1.0)
        with cond:
            cond.wait()
    """
    assert rules_of(src_ok) == []


def test_repo_concurrency_rules_clean_with_waivers():
    """The tier-1 gate (mirrors scripts/lint.py --check for the new
    rules): the package lints clean, every concurrency waiver used."""
    from pytorch_distributed_training_tpu.analysis.lint import (
        DEFAULT_WAIVERS,
        lint_paths,
    )
    from pytorch_distributed_training_tpu.analysis.waivers import (
        load_waivers,
    )

    report = lint_paths(
        [os.path.join(REPO_ROOT, "pytorch_distributed_training_tpu")],
        load_waivers(DEFAULT_WAIVERS),
    )
    concurrency_rules = {
        "thread-shared-mutable", "unlocked-rmw", "lock-order-cycle",
        "blocking-call-in-lock", "non-daemon-thread", "unbounded-wait",
    }
    active = [f for f in report.findings if f.rule in concurrency_rules]
    assert active == [], [f.format() for f in active]
    assert not report.errors


# =====================================================================
# runtime lock registry
# =====================================================================


class ListSink:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, record):
        with self._lock:
            self.records.append(dict(record))

    def flush(self, **kw):
        pass

    def of(self, kind):
        with self._lock:
            return [r for r in self.records if r.get("record") == kind]


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def test_traced_lock_stats_and_contention():
    telemetry, _sink = _registry()
    reg = LockRegistry(mode="record", registry=telemetry)
    l = lock("t.stats", registry=reg)
    assert isinstance(l, TracedLock)
    with l:
        assert held_lock_names() == ("t.stats",)
    assert held_lock_names() == ()

    # force contention: a holder thread sits on the lock while we acquire
    release = threading.Event()
    held = threading.Event()

    def holder():
        with l:
            held.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert held.wait(5)
    got = [False]

    def contender():
        with l:
            got[0] = True

    t2 = threading.Thread(target=contender, daemon=True)
    t2.start()
    time.sleep(0.05)
    release.set()
    t.join(5)
    t2.join(5)
    assert got[0]
    s = reg.summary_record()["locks"]["t.stats"]
    assert s["acquires"] == 3
    assert s["contentions"] >= 1
    assert s["hold_max_s"] > 0
    assert s["wait_max_s"] > 0


def test_lock_order_inversion_record_and_strict():
    telemetry, sink = _registry()
    reg = LockRegistry(mode="record", registry=telemetry)
    a, b = lock("A", registry=reg), lock("B", registry=reg)
    with a:
        with b:
            pass

    def inverted(result):
        try:
            with b:
                with a:
                    pass
            result.append("ok")
        except LockOrderViolation:
            result.append("raised")

    res = []
    t = threading.Thread(target=inverted, args=(res,), daemon=True)
    t.start()
    t.join(5)
    assert res == ["ok"]                        # record mode never raises
    assert reg.order_violations == 1
    [violation] = sink.of("lock_order_violation")
    assert violation["acquiring"] == "A" and violation["holding"] == ["B"]

    strict = LockRegistry(mode="strict", registry=telemetry)
    a2, b2 = lock("A", registry=strict), lock("B", registry=strict)
    with a2:
        with b2:
            pass
    res2 = []

    def inverted2():
        try:
            with b2:
                with a2:
                    pass
            res2.append("ok")
        except LockOrderViolation:
            res2.append("raised")

    t = threading.Thread(target=inverted2, daemon=True)
    t.start()
    t.join(5)
    assert res2 == ["raised"]
    # the strict raise happened BEFORE acquiring: nothing leaked as held
    assert held_lock_names() == ()


def test_mode_off_returns_plain_lock():
    reg = LockRegistry(mode="off")
    l = lock("x", registry=reg)
    assert not isinstance(l, TracedLock)
    with l:
        assert held_lock_names() == ()      # uninstrumented


def test_condition_over_traced_lock_keeps_held_stack_honest():
    telemetry, _sink = _registry()
    reg = LockRegistry(mode="record", registry=telemetry)
    l = lock("t.cond", registry=reg)
    cond = threading.Condition(l)
    observed = []

    def waiter():
        with cond:
            observed.append(("pre-wait", held_lock_names()))
            cond.wait(timeout=5)
            observed.append(("post-wait", held_lock_names()))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.05)
    # while the waiter sleeps inside cond.wait it must NOT hold the lock
    acquired = l.acquire(timeout=2)
    assert acquired
    l.release()
    with cond:
        cond.notify_all()
    t.join(5)
    assert observed == [
        ("pre-wait", ("t.cond",)), ("post-wait", ("t.cond",)),
    ]


def test_guards_flag_lock_held_across_device_boundary():
    jax = pytest.importorskip("jax")
    from pytorch_distributed_training_tpu.analysis.guards import (
        GuardSet,
        GuardViolation,
    )

    telemetry, sink = _registry()
    lock_reg = LockRegistry(mode="record", registry=telemetry)
    prev = set_lock_registry(lock_reg)
    try:
        guards = GuardSet(mode="record", registry=telemetry)
        fn = guards.wrap_jit("boundary_fn", jax.jit(lambda x: x + 1))
        l = lock("t.boundary", registry=lock_reg)
        with l:
            fn(1.0)                             # record: flagged, not fatal
        [rec] = sink.of("lock_across_device")
        assert rec["boundary"] == "boundary_fn"
        assert rec["holding"] == ["t.boundary"]

        strict = GuardSet(mode="strict", registry=telemetry)
        sfn = strict.wrap_jit("boundary_strict", jax.jit(lambda x: x * 2))
        sfn(1.0)                                # warm it OUTSIDE the lock
        with pytest.raises(GuardViolation):
            with l:
                sfn(2.0)
        # transfer_scope checks the same invariant
        with pytest.raises(GuardViolation):
            with l:
                with strict.transfer_scope("tick"):
                    pass
    finally:
        set_lock_registry(prev)


def test_serve_donation_audit_posts_first_compile_record():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.analysis.guards import GuardSet

    telemetry, sink = _registry()
    guards = GuardSet(mode="record", registry=telemetry)

    def rewrite(state, delta):
        return state + delta

    fn = guards.wrap_jit(
        "donating", jax.jit(rewrite, donate_argnums=(0,)),
        audit_donation=True,
    )
    out = fn(jnp.zeros((256,), jnp.float32), jnp.ones((256,), jnp.float32))
    assert float(out[0]) == 1.0
    [audit] = sink.of("donation_audit")
    assert audit["name"] == "donating"
    assert audit["ok"] is True and audit["aliased"] >= 1
    # one-shot: a second (warm) call must not re-audit
    fn(out, jnp.ones((256,), jnp.float32))
    assert len(sink.of("donation_audit")) == 1


def test_engine_prefill_and_decode_are_donation_audited():
    """The serve programs' post-first-compile hook end to end: building a
    tiny engine and serving one request emits a donation_audit for the
    bucket's prefill and for the decode step, both ok (the resident cache
    donation survived to the executable)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )
    from pytorch_distributed_training_tpu.serve.server import wait_until
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32",
        attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    telemetry, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=2, prompt_buckets=(8,), max_new_tokens=8),
        registry=telemetry,
    ).start()
    try:
        req = server.submit(
            np.arange(1, 6, dtype=np.int32), max_new_tokens=4
        )
        assert wait_until(req.done.is_set, timeout=120)
        assert req.status == "done"
        audits = {r["name"]: r for r in sink.of("donation_audit")}
        assert "serve_prefill_b8" in audits and "serve_decode" in audits
        assert all(a["ok"] for a in audits.values()), audits
    finally:
        server.close(drain=False)


# =====================================================================
# lint --changed + summarize locks section
# =====================================================================


def test_lint_changed_mode_runs_and_is_clean():
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import lint as lint_cli
    finally:
        sys.path.pop(0)
    files = lint_cli.changed_files("HEAD")
    assert all(f.endswith(".py") and os.path.isabs(f) for f in files)
    assert lint_cli.main(["--changed", "HEAD", "--check"]) == 0


def test_summarize_locks_section_folds_and_renders(tmp_path):
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import summarize_metrics as sm
    finally:
        sys.path.pop(0)
    records = [
        {"record": "lock_summary", "pid": 1, "mode": "record",
         "order_violations": 0, "device_boundary_holds": 0,
         "order_edges": {"a": ["b"]},
         "locks": {"serve.queue": {
             "acquires": 100, "contentions": 7, "wait_total_s": 0.1,
             "wait_max_s": 0.02, "wait_p99_s": 0.015,
             "hold_total_s": 0.5, "hold_max_s": 0.01, "hold_p99_s": 0.008,
         }}},
        # same pid again (newer cumulative snapshot wins)
        {"record": "lock_summary", "pid": 1, "mode": "record",
         "order_violations": 0, "device_boundary_holds": 0,
         "order_edges": {},
         "locks": {"serve.queue": {
             "acquires": 150, "contentions": 9, "wait_total_s": 0.2,
             "wait_max_s": 0.05, "wait_p99_s": 0.02,
             "hold_total_s": 0.7, "hold_max_s": 0.02, "hold_p99_s": 0.01,
         }}},
        {"record": "lock_summary", "pid": 2, "mode": "strict",
         "order_violations": 0, "device_boundary_holds": 0,
         "order_edges": {},
         "locks": {"serve.queue": {
             "acquires": 50, "contentions": 1, "wait_total_s": 0.01,
             "wait_max_s": 0.005, "wait_p99_s": 0.004,
             "hold_total_s": 0.2, "hold_max_s": 0.004, "hold_p99_s": 0.003,
         }}},
        {"record": "lock_order_violation", "acquiring": "A",
         "holding": ["B"], "inverts": "A -> B"},
    ]
    locks = sm.summarize_locks(records)
    assert locks["processes"] == 2
    row = locks["locks"]["serve.queue"]
    assert row["acquires"] == 200          # pid1 newest (150) + pid2 (50)
    assert row["contentions"] == 10
    assert row["wait_max_s"] == 0.05
    assert locks["order_violations"] == 1
    table = sm.render_locks_table(locks)
    assert "serve.queue" in table and "INVERSION" in table
    # end to end through the CLI
    stream = tmp_path / "metrics.jsonl"
    stream.write_text(
        "".join(json.dumps(r) + "\n" for r in records)
    )
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "locks:" in proc.stdout and "serve.queue" in proc.stdout


# =====================================================================
# THE chaos drill: 2-replica fleet + hotswap polling, strict guards +
# instrumented locks — zero lock-order violations, locks section renders
# =====================================================================


def _post_generate(port, prompt, max_new, rid, timeout=120):
    import http.client

    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=timeout
        )
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": prompt, "max_new_tokens": max_new}),
            headers={"X-Request-Id": rid},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            conn.close()
            return {"outcome": "rejected", "status": resp.status}
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        conn.close()
        last = events[-1] if events else {}
        return {
            "outcome": "done" if last.get("event") == "done" else "bad",
            "events": events,
        }
    except Exception as e:      # pragma: no cover - drill diagnostics
        return {"outcome": "exception", "error": repr(e)}


@pytest.mark.chaos
@pytest.mark.serve
def test_fleet_hotswap_under_strict_guards_zero_lock_violations(tmp_path):
    """Acceptance drill: a 2-replica fleet (strict guards + instrumented
    locks in every process) serves a closed loop while a checkpoint step
    publishes and hot-swap-polls across the pool. Zero lock-order
    violations anywhere (the strict registries would have raised; the
    merged telemetry must hold no lock_order_violation records), every
    replica's lock_summary lands in its stream, and the summarize
    "locks" section renders from the merged telemetry."""
    jax = pytest.importorskip("jax")
    import numpy as np

    from pytorch_distributed_training_tpu.serve import (
        publish_params_checkpoint,
    )
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )
    from pytorch_distributed_training_tpu.serve.router import (
        RouterConfig,
        make_router_http_server,
    )
    from pytorch_distributed_training_tpu.serve.server import wait_until

    # strict lock registry for THIS (fleet/router) process: an inversion
    # in the router/breaker/watcher locks would raise mid-drill
    strict_locks = LockRegistry(mode="strict")
    prev_locks = set_lock_registry(strict_locks)

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    pA = model.init(jax.random.key(0), np.zeros((1, 8), np.int32))["params"]
    pB = jax.tree.map(lambda x: x * 1.01, pA)
    ckpt_dir = str(tmp_path / "ckpt")
    publish_params_checkpoint(ckpt_dir, 1, pA)

    reg, sink = _registry()
    metrics_root = tmp_path / "metrics"
    fleet = ServeFleet(
        FleetConfig(
            num_replicas=2,
            replica_args=(
                "--model", "gpt2-tiny", "--num-slots", "2",
                "--prompt-buckets", "16,32", "--max-new-tokens-cap", "32",
                "--queue-depth", "16", "--stall-timeout-s", "10",
                "--checkpoint-dir", ckpt_dir,
            ),
            replica_extra_args={
                i: ("--metrics-dir", str(metrics_root / f"r{i}"))
                for i in range(2)
            },
            # strict guards AND strict lock registry inside each replica
            replica_env={"PDT_TPU_GUARDS": "strict"},
            max_restarts=1,
            backoff_s=0.2,
            drain_timeout_s=30.0,
        ),
        RouterConfig(
            health_interval_s=0.05, health_timeout_s=1.0,
            retry_backoff_s=0.02, retry_backoff_max_s=0.1,
            ttfb_timeout_s=60.0,
        ),
        registry=reg,
    ).start()
    httpd = None
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        fleet.enable_hotswap(ckpt_dir, poll_interval_s=0.1)
        httpd = make_router_http_server(fleet.router)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        # closed-loop wave while step 2 publishes and rolls out
        n = 6
        results = [None] * n
        threads = []
        for i in range(n):
            def run(i=i):
                results[i] = _post_generate(
                    port, f"lock drill request {i}", 8, f"lk-{i}"
                )
            t = threading.Thread(target=run, daemon=True)
            threads.append(t)
            t.start()
        publish_params_checkpoint(ckpt_dir, 2, pB)
        for t in threads:
            t.join(180)
        assert all(not t.is_alive() for t in threads)
        assert [r["outcome"] for r in results] == ["done"] * n, results

        # the rollout converged on both replicas, zero version skew
        assert wait_until(
            lambda: fleet.router.stats()["weights"] == {"r0": 2, "r1": 2}
            and fleet.router.stats()["version_skew"] == 0,
            timeout=120,
        ), fleet.router.stats()
    finally:
        if httpd is not None:
            httpd.shutdown()
        # DRAIN stop: each replica's serve_lm exits through its finally,
        # emitting serve_summary + lock_summary into its metrics dir
        fleet.stop(drain=True)
        set_lock_registry(prev_locks)

    # the fleet process itself observed no inversion (strict would have
    # raised) and its registry agrees
    assert strict_locks.order_violations == 0

    # merge the fleet-process stream with both replica streams
    merged = []
    merged.extend(sink.records)
    for i in range(2):
        stream = metrics_root / f"r{i}" / "metrics.jsonl"
        assert stream.exists(), f"replica {i} wrote no metrics stream"
        for line in stream.read_text().splitlines():
            try:
                merged.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    merged.append(strict_locks.summary_record())

    summaries = [r for r in merged if r.get("record") == "lock_summary"]
    assert len({r.get("pid") for r in summaries}) >= 3   # 2 replicas + us
    assert [
        r for r in merged if r.get("record") == "lock_order_violation"
    ] == []
    # the replicas really ran the instrumented hot locks
    replica_locks = set()
    for r in summaries:
        replica_locks.update((r.get("locks") or {}))
    assert "serve.queue" in replica_locks
    assert "serve.engine.swap" in replica_locks

    # the summarize "locks" section renders from the recorded telemetry
    stream = tmp_path / "merged.jsonl"
    stream.write_text("".join(json.dumps(r) + "\n" for r in merged))
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream),
         "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout)
    locks = summary["locks"]
    assert locks["order_violations"] == 0
    assert locks["device_boundary_holds"] == 0
    assert locks["processes"] >= 3
    assert "serve.queue" in locks["locks"]
    table = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert table.returncode == 0
    assert "locks:" in table.stdout and "[clean]" in table.stdout
