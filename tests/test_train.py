"""Train-layer tests (SURVEY.md §4 strategy): optimizer math vs closed form,
schedule values, grad-accum equivalence, DP=8 vs single-device parity, and
masked metrics — all the verification the reference never had."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train import (
    MetricAccumulator,
    adamw_with_schedule,
    create_train_state,
    linear_warmup_schedule,
    make_eval_step,
    make_train_step,
)
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)


def make_batch(rng, accum, micro, seq=16, vocab=1000, num_labels=2):
    return {
        "input_ids": rng.integers(0, vocab, (accum, micro, seq)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, seq), np.int32),
        "token_type_ids": np.zeros((accum, micro, seq), np.int32),
        "labels": rng.integers(0, num_labels, (accum, micro)).astype(np.int32),
    }


def tiny_state(total_steps=100, **train_kw):
    cfg = model_preset("tiny", compute_dtype="float32", hidden_dropout=0.0,
                       attention_dropout=0.0)
    tcfg = TrainConfig(**train_kw)
    model = BertForSequenceClassification(cfg)
    tx, _ = adamw_with_schedule(tcfg, total_steps)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    return create_train_state(model, tx, jax.random.key(0), example)


# ---------------------------------------------------------------- optimizer

def test_adamw_matches_closed_form():
    """One AdamW step on a scalar param vs the hand-derived update
    (bias-corrected Adam + decoupled weight decay — the semantics of
    transformers AdamW(correct_bias=True) the reference relies on)."""
    lr, b1, b2, eps, wd = 0.1, 0.9, 0.999, 1e-8, 0.01
    tx = optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=wd)
    p = jnp.array([2.0])
    g = jnp.array([0.5])
    opt_state = tx.init(p)
    updates, _ = tx.update(g, opt_state, p)
    new_p = optax.apply_updates(p, updates)

    m = (1 - b1) * 0.5 / (1 - b1)        # bias-corrected first moment
    v = (1 - b2) * 0.25 / (1 - b2)       # bias-corrected second moment
    expected = 2.0 - lr * (m / (np.sqrt(v) + eps) + wd * 2.0)
    np.testing.assert_allclose(np.asarray(new_p), [expected], rtol=1e-6)


def test_linear_schedule_values():
    sched = linear_warmup_schedule(2e-5, warmup_steps=100, total_steps=1000)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(50)), 1e-5, rtol=1e-6)
    np.testing.assert_allclose(float(sched(100)), 2e-5, rtol=1e-6)
    np.testing.assert_allclose(float(sched(550)), 1e-5, rtol=1e-2)
    np.testing.assert_allclose(float(sched(1000)), 0.0, atol=1e-12)


def test_grad_clip_is_off_by_default_and_togglable():
    # warmup LR at step 0 is 0, so compare the SECOND update; a huge gradient
    # fed to clipped AdamW leaves a tiny clipped moment vs an O(1) unclipped one.
    def second_update(tcfg):
        tx, _ = adamw_with_schedule(tcfg, 10)
        p = {"w": jnp.array([1.0])}
        g = {"w": jnp.array([1e6])}
        st = tx.init(p)
        up, st = tx.update(g, st, p)
        p2 = optax.apply_updates(p, up)
        up2, _ = tx.update(g, st, p2)
        return abs(float(up2["w"][0]))

    assert second_update(TrainConfig(max_grad_norm=1e-9)) < 1e-6
    assert second_update(TrainConfig()) > 1e-7


# ---------------------------------------------------------------- train step

def test_grad_accum_equals_full_batch():
    """accum=4 × micro=4 must produce (numerically) the same update as
    accum=1 × micro=16 — the structural no_sync equivalence."""
    rng = np.random.default_rng(0)
    flat = make_batch(rng, 1, 16)
    split = {k: v.reshape(4, 4, *v.shape[2:]) for k, v in flat.items()}

    s1 = tiny_state()
    s2 = tiny_state()  # identical params (same seed); donation-safe
    step1 = make_train_step(grad_accum_steps=1)
    step4 = make_train_step(grad_accum_steps=4)
    s1b, m1 = step1(s1, jax.tree.map(jnp.asarray, flat))
    s2b, m4 = step4(s2, jax.tree.map(jnp.asarray, split))
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s1b.params)])
    b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s2b.params)])
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_step_counts_updates_not_microbatches():
    s = tiny_state()
    step = make_train_step(grad_accum_steps=4)
    batch = jax.tree.map(jnp.asarray, make_batch(np.random.default_rng(1), 4, 4))
    s, _ = step(s, batch)
    assert int(s.step) == 1  # one update per global batch, not per microbatch


def test_loss_decreases_single_device():
    s = tiny_state()
    step = make_train_step(grad_accum_steps=2)
    rng = np.random.default_rng(2)
    # learnable rule: label = first token parity
    losses = []
    for i in range(12):
        b = make_batch(rng, 2, 8)
        b["labels"] = (b["input_ids"][:, :, 0] % 2).astype(np.int32)
        s, m = step(s, jax.tree.map(jnp.asarray, b))
        losses.append(float(m["loss"]))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def _place_train_batch(mesh, batch):
    """Place an [accum, micro, ...] batch exactly as production does —
    through comms.ingest.make_global_batch with the train pspec — so the
    dp/fsdp/tp parity tests always exercise the real layout contract."""
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    return make_global_batch(
        mesh,
        jax.tree.map(np.asarray, batch),
        pspec=TRAIN_BATCH_PSPEC,
    )


def test_dp8_matches_single_device(eight_devices):
    """The implicit claim of the reference's two scripts — distributed and
    single-device training compute the same thing — made explicit
    (SURVEY.md §4 'parity')."""
    mesh = build_mesh(MeshConfig(data=8))
    batch = make_batch(np.random.default_rng(3), 2, 16)

    s_single = tiny_state()
    s_dp = tiny_state()  # identical params (same seed); donation-safe

    step_single = make_train_step(grad_accum_steps=2)
    s1, m1 = step_single(s_single, jax.tree.map(jnp.asarray, batch))

    policy = ShardingPolicy()  # pure DP: replicated params
    shardings = state_shardings(s_dp, policy, mesh)
    s_dp = shard_state(s_dp, shardings)
    step_dp = make_train_step(
        grad_accum_steps=2, mesh=mesh, state_shardings=shardings
    )
    s2, m2 = step_dp(s_dp, _place_train_batch(mesh, batch))
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=2e-5)
    a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s1.params)])
    b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s2.params)])
    np.testing.assert_allclose(a, b, atol=2e-5)


def test_fsdp_shards_params_and_matches(eight_devices):
    """FSDP policy: params shard over the fsdp axis, loss matches DP."""
    mesh_dp = build_mesh(MeshConfig(data=8))
    mesh_fsdp = build_mesh(MeshConfig(data=2, fsdp=4))
    batch = make_batch(np.random.default_rng(4), 2, 16)

    results = {}
    for name, mesh, policy in [
        ("dp", mesh_dp, ShardingPolicy()),
        ("fsdp", mesh_fsdp, ShardingPolicy(fsdp=True, fsdp_min_size=128)),
    ]:
        s = tiny_state()
        shardings = state_shardings(s, policy, mesh)
        s = shard_state(s, shardings)
        if name == "fsdp":
            specs = {
                str(jax.tree_util.keystr(p)): x.sharding.spec
                for p, x in jax.tree_util.tree_flatten_with_path(s.params)[0]
            }
            sharded = [k for k, v in specs.items() if "fsdp" in str(v)]
            assert sharded, f"no param got fsdp-sharded: {specs}"
        step = make_train_step(grad_accum_steps=2, mesh=mesh,
                               state_shardings=shardings)
        _, m = step(s, _place_train_batch(mesh, batch))
        results[name] = float(m["loss"])
    np.testing.assert_allclose(results["dp"], results["fsdp"], rtol=2e-5)


def test_tp_shards_matmuls_and_matches(eight_devices):
    """Tensor-parallel policy: attention/mlp kernels shard over the model
    axis (Megatron-style), loss and updated params match pure DP."""
    mesh_dp = build_mesh(MeshConfig(data=8))
    mesh_tp = build_mesh(MeshConfig(data=2, model=4))
    batch = make_batch(np.random.default_rng(5), 2, 16)

    results = {}
    for name, mesh, policy in [
        ("dp", mesh_dp, ShardingPolicy()),
        ("tp", mesh_tp, ShardingPolicy(tp=True)),
    ]:
        s = tiny_state()
        shardings = state_shardings(s, policy, mesh)
        s = shard_state(s, shardings)
        if name == "tp":
            specs = {
                str(jax.tree_util.keystr(p)): x.sharding.spec
                for p, x in jax.tree_util.tree_flatten_with_path(s.params)[0]
            }
            sharded = [k for k, v in specs.items() if "model" in str(v)]
            assert sharded, f"no param got tp-sharded: {specs}"
        step = make_train_step(grad_accum_steps=2, mesh=mesh,
                               state_shardings=shardings)
        s2, m = step(s, _place_train_batch(mesh, batch))
        results[name] = (
            float(m["loss"]),
            np.concatenate(
                [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(s2.params)]
            ),
        )
    np.testing.assert_allclose(results["dp"][0], results["tp"][0], rtol=2e-5)
    np.testing.assert_allclose(results["dp"][1], results["tp"][1], atol=3e-5)


# ---------------------------------------------------------------- eval step

def test_eval_counts_and_masking():
    s = tiny_state()
    ev = make_eval_step()
    rng = np.random.default_rng(5)
    batch = {
        "input_ids": rng.integers(0, 1000, (8, 16)).astype(np.int32),
        "attention_mask": np.ones((8, 16), np.int32),
        "token_type_ids": np.zeros((8, 16), np.int32),
        "labels": rng.integers(0, 2, (8,)).astype(np.int32),
        "valid": np.array([1, 1, 1, 1, 1, 0, 0, 0], np.int32),
    }
    counts = ev(s, jax.tree.map(jnp.asarray, batch))
    assert float(counts["total"]) == 5.0  # padding rows excluded
    assert float(counts["correct"]) <= 5.0
    # confusion identity: tp+fp+fn <= ways that preds/labels disagree+agree
    assert float(counts["tp"]) + float(counts["fn"]) == float(
        ((batch["labels"] == 1) * batch["valid"]).sum()
    )


def test_metric_accumulator_matches_sklearn_formulas():
    rng = np.random.default_rng(6)
    preds = rng.integers(0, 2, 200)
    labels = rng.integers(0, 2, 200)
    acc = MetricAccumulator(num_labels=2)
    for i in range(0, 200, 50):  # folded in 4 batches
        p, l = preds[i:i+50], labels[i:i+50]
        acc.update({
            "correct": (p == l).sum(), "total": 50,
            "tp": ((p == 1) & (l == 1)).sum(),
            "fp": ((p == 1) & (l == 0)).sum(),
            "fn": ((p == 0) & (l == 1)).sum(),
        })
    out = acc.compute()
    np.testing.assert_allclose(out["accuracy"], (preds == labels).mean())
    tp = ((preds == 1) & (labels == 1)).sum()
    fp = ((preds == 1) & (labels == 0)).sum()
    fn = ((preds == 0) & (labels == 1)).sum()
    np.testing.assert_allclose(out["f1"], 2 * tp / (2 * tp + fp + fn))


def test_supervisor_restarts_and_resumes(tmp_path):
    """run_with_restarts retries a transiently-failing attempt; combined
    with checkpoint_dir+resume the retry continues the saved trajectory
    (the framework's elastic-recovery story, SURVEY.md §5)."""
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    calls = []

    def attempt(i):
        calls.append(i)
        if i < 2:
            raise RuntimeError(f"injected failure {i}")
        return "done"

    out = run_with_restarts(attempt, max_restarts=3, backoff_s=0.01)
    assert out == "done" and calls == [0, 1, 2]

    with pytest.raises(RuntimeError):
        run_with_restarts(
            lambda i: (_ for _ in ()).throw(RuntimeError("always")),
            max_restarts=1,
            backoff_s=0.01,
        )


def test_fused_adamw_matches_optax():
    """train/fused_adamw.py with fp32 moments must match optax.adamw
    step-for-step (it is the default optimizer via adamw_with_schedule).
    Moments are bit-identical; updates agree to ~1 ulp/step (XLA fuses the
    two bias-correction divisions differently), hence rtol 1e-6."""
    import optax

    from pytorch_distributed_training_tpu.train.fused_adamw import adamw_fused

    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32),
    }
    sched = optax.linear_schedule(1e-3, 0.0, 50)
    kw = dict(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    tx_f = adamw_fused(sched, **kw)
    tx_o = optax.adamw(sched, **kw)
    s_f, s_o = tx_f.init(params), tx_o.init(params)
    p_f, p_o = params, params
    for i in range(5):
        g = {
            "w": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32),
        }
        u_f, s_f = tx_f.update(g, s_f, p_f)
        u_o, s_o = tx_o.update(g, s_o, p_o)
        p_f = optax.apply_updates(p_f, u_f)
        p_o = optax.apply_updates(p_o, u_o)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(p_f[k]), np.asarray(p_o[k]), rtol=1e-6, atol=1e-8,
                err_msg=f"step {i} param {k}",
            )
    # bf16 moments change storage only, never the tree structure
    tx_h = adamw_fused(sched, mu_dtype="bfloat16", nu_dtype="bfloat16", **kw)
    s_h = tx_h.init(params)
    assert jax.tree_util.tree_structure(s_h) == jax.tree_util.tree_structure(
        s_f
    )


def test_chained_steps_match_per_step():
    """chain_steps=k (one dispatch, k optimizer steps via lax.scan) must
    reproduce k per-step dispatches exactly."""
    rng = np.random.default_rng(7)
    batches = [make_batch(rng, 2, 8) for _ in range(3)]

    s1 = tiny_state()
    step = make_train_step(grad_accum_steps=2)
    losses = []
    for b in batches:
        s1, m1 = step(s1, jax.tree.map(jnp.asarray, b))
        losses.append(float(m1["loss"]))

    s2 = tiny_state()
    chained = make_train_step(grad_accum_steps=2, chain_steps=3)
    stacked = {
        k: jnp.stack([jnp.asarray(b[k]) for b in batches]) for k in batches[0]
    }
    s2, m2 = chained(s2, stacked)

    assert int(s1.step) == int(s2.step) == 3
    # the chained step reports the chain-MEAN loss (so epoch averages weight
    # every step equally); other metrics are last-step
    np.testing.assert_allclose(
        float(np.mean(losses)), float(m2["loss"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(m1["grad_norm"]), float(m2["grad_norm"]), rtol=1e-6
    )
    a = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s1.params)])
    b = np.concatenate([np.ravel(x) for x in jax.tree.leaves(s2.params)])
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_unroll_accum_rolled_matches_unrolled():
    """The rolled and unrolled accumulation scans are the same math —
    forcing either via make_train_step(unroll_accum=...) must produce
    identical losses and updated params (the knob exists purely for the
    peak-memory difference, NOTES.md round-4)."""
    batch = jax.tree.map(
        jnp.asarray, make_batch(np.random.default_rng(11), 3, 8)
    )
    outs = {}
    for name, unroll in (("rolled", False), ("unrolled", True)):
        s = tiny_state()
        step = make_train_step(
            grad_accum_steps=3, log_grad_norm=False, unroll_accum=unroll
        )
        s2, m = step(s, batch)
        outs[name] = (
            float(m["loss"]),
            np.concatenate(
                [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(s2.params)]
            ),
        )
    np.testing.assert_allclose(
        outs["rolled"][0], outs["unrolled"][0], rtol=1e-6
    )
    np.testing.assert_allclose(
        outs["rolled"][1], outs["unrolled"][1], atol=1e-6
    )
