"""Serving performance acceptance (opt-in: ``-m perf``).

Drives ``bench.py --serve`` in a subprocess: a closed-loop load generator
over the continuous-batching engine vs sequential one-shot ``generate()``
calls on the SAME prompt mix, both compile-warmed. Asserts the PR's
acceptance criterion — with >= 2 decode slots the engine sustains strictly
higher aggregate tokens/sec — plus the artifact contract (latency
percentiles present, request accounting adds up). Timing-based, so it
stays out of tier-1 (conftest auto-skips without ``-m perf``).
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.perf, pytest.mark.serve]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_serve_bench_beats_sequential(tmp_path):
    out = tmp_path / "BENCH_serve.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--serve", "--serve-requests", "16", "--serve-slots", "4",
            "--serve-concurrency", "6", "--serve-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    eng, seq = result["engine"], result["sequential"]
    # the acceptance criterion: continuous batching over >= 2 slots beats
    # sequential one-shot generation on aggregate tokens/sec
    assert eng["slots"] >= 2
    assert eng["tokens_per_s"] > seq["tokens_per_s"], result
    assert result["speedup"] > 1.0

    # same workload on both sides, every request served
    assert eng["tokens"] == seq["tokens"]
    assert eng["requests"] == 16

    # the artifact carries real latency percentiles
    for block in ("ttft_s", "tpot_s", "queue_wait_s"):
        stats = eng[block]
        assert stats["count"] > 0
        assert stats["p50"] <= stats["p95"] <= stats["p99"]
