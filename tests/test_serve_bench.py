"""Serving performance acceptance (opt-in: ``-m perf``).

Drives ``bench.py --serve`` in a subprocess: a closed-loop load generator
over the continuous-batching engine vs sequential one-shot ``generate()``
calls on the SAME prompt mix, both compile-warmed. Asserts the PR's
acceptance criterion — with >= 2 decode slots the engine sustains strictly
higher aggregate tokens/sec — plus the artifact contract (latency
percentiles present, request accounting adds up). Timing-based, so it
stays out of tier-1 (conftest auto-skips without ``-m perf``).
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.perf, pytest.mark.serve]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_serve_bench_beats_sequential(tmp_path):
    out = tmp_path / "BENCH_serve.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--serve", "--serve-requests", "16", "--serve-slots", "4",
            "--serve-concurrency", "6", "--serve-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    eng, seq = result["engine"], result["sequential"]
    # the acceptance criterion: continuous batching over >= 2 slots beats
    # sequential one-shot generation on aggregate tokens/sec
    assert eng["slots"] >= 2
    assert eng["tokens_per_s"] > seq["tokens_per_s"], result
    assert result["speedup"] > 1.0

    # same workload on both sides, every request served
    assert eng["tokens"] == seq["tokens"]
    assert eng["requests"] == 16

    # the artifact carries real latency percentiles
    for block in ("ttft_s", "tpot_s", "queue_wait_s"):
        stats = eng[block]
        assert stats["count"] > 0
        assert stats["p50"] <= stats["p95"] <= stats["p99"]


@pytest.mark.chaos
def test_fleet_bench_availability_under_replica_kill(tmp_path):
    """bench.py --fleet: 2 supervised replicas behind the router, one
    SIGKILLed mid-load. Every request must end explicitly (done /
    retryable error / rejected — zero hangs), the supervisor must burn a
    restart respawning the victim, and the artifact must carry the
    availability + p99-delta numbers the fleet dashboards track."""
    out = tmp_path / "BENCH_fleet.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--fleet", "--fleet-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    base, chaos = result["baseline"], result["chaos"]
    # healthy pool: everything completes
    assert base["availability"] == 1.0 and base["hung_or_bad"] == 0
    # chaos: zero hung waiters — every answer is explicit — and the
    # surviving replica keeps the pool mostly available
    assert chaos["hung_or_bad"] == 0, result
    assert chaos["explicit_answer_rate"] == 1.0
    assert chaos["availability"] >= 0.5, result
    # the kill really happened and supervision recovered from it
    assert result["recovery"]["replica0_restarts_used"] >= 1
    assert result["recovery"]["pool_recovered"] is True
    assert result["recovery"]["post_recovery_request"] == "done"
    # latency artifact present for the dashboard delta
    assert base["p99_s"] and chaos["p99_s"] and result["p99_delta"]


@pytest.mark.swap
def test_swap_bench_p99_delta_and_convergence(tmp_path):
    """bench.py --swap: a new checkpoint step published + rolled across a
    2-replica pool mid-load. The rollout must cost at most a modest tail
    penalty (p99 delta <= 1.5x the healthy baseline), fail ZERO requests,
    converge the whole pool (router skew 0) without a replica restart,
    and carry the publish-to-convergence time the rollout dashboards
    track."""
    out = tmp_path / "BENCH_swap.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--swap", "--swap-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    base, swap = result["baseline"], result["swap"]
    assert base["done"] == base["requests"] and base["failed"] == 0
    assert swap["done"] == swap["requests"] and swap["failed"] == 0
    assert result["failed_requests"] == 0

    # the acceptance gate: swapping under load costs <= 1.5x p99
    assert result["p99_delta"] is not None
    assert result["p99_delta"] <= 1.5, result

    # the pool converged on the new step with no restart
    assert result["converged"] is True
    assert result["version_skew"] == 0
    assert set(result["weights"].values()) == {2}
    assert result["convergence_s"] is not None
    assert result["replica_restarts"] == [0, 0]
    assert result["post_rollout_request"] == "done"
    assert result["hotswap"]["rollouts_converged"] >= 1
