"""Live checkpoint hot-swap tests (serve/hotswap.py + the engine's swap
protocol + the fleet's rolling rollout).

Three tiers, all CPU and tier-1 (``-m swap`` selects just this file):

- watcher unit tests against hand-built step directories (manifest-sealed
  fake steps — no orbax, no model): admission order, monotonicity,
  partial-publish tolerance, re-publish rejection, blocklisting, clean
  shutdown with a poll in flight;
- in-process engine/server tests (gpt2-tiny): a swap is token-identical
  to serving the new weights from scratch, clean under strict guards (no
  retrace, no implicit transfer), applied between ticks with in-flight
  requests finishing, rolled back when the first post-swap tick fails,
  and rejected outright for shape-mismatched trees; the ``POST /swap``
  endpoint drives the same path over HTTP;
- THE chaos drill: a 2-replica fleet under closed-loop load, a corrupt
  checkpoint published mid-serve (``PDT_TPU_FAULT=corrupt_ckpt_swap``) —
  zero failed requests, a recorded rollback on every replica, the step
  blocklisted, and a subsequently published good step serving on ALL
  replicas (router skew 0) with no replica restart and strict guards
  clean.
"""

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_training_tpu.serve.hotswap import (
    CheckpointWatcher,
    manifest_digest,
    scan_step_dirs,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.train import manifest

pytestmark = [pytest.mark.serve, pytest.mark.swap]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class ListSink:
    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        with self._lock:
            self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        with self._lock:
            return [r for r in self.records if r.get("record") == kind]


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


# =====================================================================
# watcher: hand-built manifest-sealed steps, no orbax
# =====================================================================


def _publish_fake(directory, step: int, payload: bytes) -> str:
    """A minimal sealed step: one data file + a real integrity manifest
    (the same build/write path the checkpointer uses)."""
    path = os.path.join(str(directory), str(step))
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "weights.bin"), "wb") as f:
        f.write(payload)
    manifest.write_manifest(path, manifest.build_manifest(path, step))
    return path


class Applier:
    """Recording apply_fn whose verdict per step is scriptable."""

    def __init__(self, fail_steps=()):
        self.calls = []
        self.fail_steps = set(fail_steps)

    def __call__(self, step: int) -> bool:
        self.calls.append(step)
        return step not in self.fail_steps


def _watcher(directory, apply_fn, reg, **kw):
    kw.setdefault("verify_level", "digest")
    kw.setdefault("start_step", 0)
    return CheckpointWatcher(
        str(directory), apply_fn, registry=reg, **kw
    )


def test_scan_step_dirs_ignores_non_steps(tmp_path):
    _publish_fake(tmp_path, 3, b"three")
    _publish_fake(tmp_path, 10, b"ten")
    os.makedirs(tmp_path / "tmp_orbax_thing")
    (tmp_path / "metrics.jsonl").write_text("{}\n")
    assert scan_step_dirs(str(tmp_path)) == [3, 10]
    assert scan_step_dirs(str(tmp_path / "missing")) == []


def test_watcher_admits_newest_verified_once(tmp_path):
    reg, sink = _registry()
    apply = Applier()
    w = _watcher(tmp_path, apply, reg)
    assert w.poll_once() is None        # empty dir: nothing to admit
    _publish_fake(tmp_path, 1, b"v1")
    _publish_fake(tmp_path, 2, b"v2")
    assert w.poll_once() == 2           # newest verified wins, 1 skipped
    assert apply.calls == [2]
    assert w.current_step == 2
    assert w.poll_once() is None        # never admitted twice
    assert apply.calls == [2]
    _publish_fake(tmp_path, 5, b"v5")
    assert w.poll_once() == 5
    assert [r["step"] for r in sink.of("swap_admitted")] == [2, 5]


def test_watcher_baseline_without_applying(tmp_path):
    """start_step=None: the first poll records what is already on disk as
    the serving baseline — the caller booted from it, re-applying would be
    a spurious swap."""
    reg, sink = _registry()
    _publish_fake(tmp_path, 4, b"v4")
    apply = Applier()
    w = _watcher(tmp_path, apply, reg, start_step=None)
    assert w.poll_once() is None
    assert w.current_step == 4 and apply.calls == []
    assert sink.of("swap_baseline")[0]["step"] == 4
    _publish_fake(tmp_path, 6, b"v6")
    assert w.poll_once() == 6


def test_watcher_skips_partial_publish_then_admits(tmp_path):
    """A step directory appearing mid-poll without its manifest seal (or
    failing verification) is 'in flight', not poisoned: skipped without
    blocklisting, admitted once the seal lands intact."""
    reg, _sink = _registry()
    apply = Applier()
    w = _watcher(tmp_path, apply, reg)
    path = os.path.join(str(tmp_path), "3")
    os.makedirs(path)
    with open(os.path.join(path, "weights.bin"), "wb") as f:
        f.write(b"partial")
    assert w.poll_once() is None        # no manifest yet
    assert 3 not in w.blocklist
    manifest.write_manifest(path, manifest.build_manifest(path, 3))
    # seal present but bytes torn (size intact, content flipped): still
    # not admitted at digest level, still not blocklisted
    with open(os.path.join(path, "weights.bin"), "r+b") as f:
        f.write(b"PARTIAL")
    assert w.poll_once() is None
    assert 3 not in w.blocklist
    with open(os.path.join(path, "weights.bin"), "r+b") as f:
        f.write(b"partial")             # publisher finishes for real
    assert w.poll_once() == 3
    assert apply.calls == [3]


def test_watcher_rejects_out_of_order_older_step(tmp_path):
    reg, sink = _registry()
    apply = Applier()
    _publish_fake(tmp_path, 5, b"v5")
    w = _watcher(tmp_path, apply, reg)
    assert w.poll_once() == 5
    _publish_fake(tmp_path, 3, b"v3-late")  # published out of order
    assert w.poll_once() is None
    assert apply.calls == [5]               # never applied, never regressed
    rejects = sink.of("swap_rejected")
    assert [r["step"] for r in rejects] == [3]
    assert "older" in rejects[0]["reason"]
    assert w.poll_once() is None            # rejected once, not per poll
    assert [r["step"] for r in sink.of("swap_rejected")] == [3]


def test_watcher_ignores_preexisting_retention_history(tmp_path):
    """Older steps already in the directory at startup (keep=N retention)
    are history, not an out-of-order publish: no rejection records, no
    applies — and the newest verified one is still admitted normally."""
    reg, sink = _registry()
    _publish_fake(tmp_path, 2, b"v2")
    _publish_fake(tmp_path, 4, b"v4")
    _publish_fake(tmp_path, 6, b"v6")
    apply = Applier()
    w = _watcher(tmp_path, apply, reg, start_step=4)  # booted from 4
    assert w.poll_once() == 6
    assert w.poll_once() is None
    assert apply.calls == [6]
    assert sink.of("swap_rejected") == []   # step 2 is history, not stale


def test_watcher_rejects_republished_step_with_different_digests(tmp_path):
    reg, sink = _registry()
    apply = Applier()
    w = _watcher(tmp_path, apply, reg)
    _publish_fake(tmp_path, 2, b"sealed-once")
    assert w.poll_once() == 2
    # a publisher rewrites the SAME step with different bytes + manifest —
    # sealed steps are immutable, this must be rejected and logged
    _publish_fake(tmp_path, 2, b"sealed-TWICE-different")
    assert w.poll_once() is None
    assert apply.calls == [2]
    rejects = sink.of("swap_rejected")
    assert any(
        r["step"] == 2 and "republished" in r["reason"] for r in rejects
    )
    assert 2 in w.blocklist
    assert w.current_step == 2


def test_watcher_blocklists_failed_apply_and_recovers_on_next_step(tmp_path):
    reg, sink = _registry()
    apply = Applier(fail_steps={2})
    w = _watcher(tmp_path, apply, reg)
    _publish_fake(tmp_path, 2, b"poisoned")
    assert w.poll_once() is None
    assert apply.calls == [2]
    assert 2 in w.blocklist
    assert sink.of("swap_blocklisted")[0]["step"] == 2
    assert w.poll_once() is None            # no poisoned-step retry loop
    assert apply.calls == [2]
    _publish_fake(tmp_path, 3, b"good")
    assert w.poll_once() == 3               # recovery = the next good step
    assert apply.calls == [2, 3]


def test_watcher_manifest_digest_distinguishes_content(tmp_path):
    a = _publish_fake(tmp_path, 1, b"content-a")
    da = manifest_digest(manifest.read_manifest(a))
    b = _publish_fake(tmp_path, 2, b"content-b")
    db = manifest_digest(manifest.read_manifest(b))
    assert da != db
    assert da == manifest_digest(manifest.read_manifest(a))


def test_watcher_clean_shutdown_with_poll_in_flight(tmp_path):
    """close() while an apply is running: the in-flight poll finishes (a
    swap is never torn by shutdown), the thread exits, no further polls."""
    reg, _sink = _registry()
    started = threading.Event()
    release = threading.Event()
    calls = []

    def slow_apply(step):
        calls.append(step)
        started.set()
        release.wait(5.0)
        return True

    w = _watcher(tmp_path, slow_apply, reg, poll_interval_s=0.01)
    _publish_fake(tmp_path, 1, b"v1")
    w.start()
    assert started.wait(10.0)
    closer = threading.Thread(target=w.close, daemon=True)
    closer.start()
    time.sleep(0.05)
    release.set()                       # let the in-flight apply finish
    closer.join(10.0)
    assert not closer.is_alive()
    assert calls == [1]
    assert w.current_step == 1          # the in-flight swap completed
    time.sleep(0.05)
    assert w.polls >= 1 and calls == [1]    # and nothing polled after


def test_swap_fault_spec_parsing_and_fleet_routing():
    from pytorch_distributed_training_tpu.faults.inject import FaultPlan
    from pytorch_distributed_training_tpu.serve.fleet import (
        split_fault_specs,
    )

    plan = FaultPlan.parse(
        "corrupt_ckpt_swap:2,swap_crash:0,swap_slow:3:0.5"
    )
    kinds = [(s.kind, s.step, s.factor) for s in plan.specs]
    assert kinds == [
        ("corrupt_ckpt_swap", 2, 1.0),
        ("swap_crash", 0, 1.0),         # checkpoint step 0 is legal
        ("swap_slow", 3, 0.5),
    ]
    assert FaultPlan.parse("swap_slow:3").specs[0].factor == 2.0
    for bad in ("corrupt_ckpt_swap:-1", "swap_crash:2:9", "swap_slow:1:0"):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)
    # swap kinds are serve-scoped: routed per replica by @rank
    routed = split_fault_specs("corrupt_ckpt_swap:2,corrupt_ckpt_swap:2@1")
    assert routed == {0: "corrupt_ckpt_swap:2", 1: "corrupt_ckpt_swap:2"}


# =====================================================================
# engine + server: the swap itself (gpt2-tiny, in process)
# =====================================================================


@pytest.fixture(scope="module")
def lm():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)

    def params_for(seed):
        return model.init(
            jax.random.key(seed), jnp.ones((2, 16), jnp.int32)
        )["params"]

    return model, params_for(0), params_for(7)


def _server(lm, reg=None, *, guards_mode="strict", **kw):
    from pytorch_distributed_training_tpu.analysis.guards import GuardSet
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )

    model, pA, _pB = lm
    kw.setdefault("queue_depth", 8)
    kw.setdefault("weights_step", 1)
    return InferenceServer(
        model, pA,
        EngineConfig(num_slots=2, prompt_buckets=(8,), max_new_tokens=32),
        registry=reg,
        guards=GuardSet(mode=guards_mode, registry=reg),
        **kw,
    )


def _prompt(model, n=5, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, model.config.vocab_size, n).astype(np.int32)


def _one_shot(model, params, prompt, n):
    from pytorch_distributed_training_tpu.models.generate import generate

    out = np.asarray(generate(model, params, prompt[None],
                              max_new_tokens=n))
    return list(out[0, len(prompt):])


def test_swap_is_token_identical_and_guard_clean(lm):
    """The acceptance core: after a live swap, greedy decode is token-
    identical to serving the new weights from scratch; the swap neither
    retraces nor implicitly transfers (PDT_TPU_GUARDS=strict clean); the
    KV cache survives (an in-flight request keeps streaming through the
    swap); serve_request telemetry attributes every answer to a weights
    version."""
    model, pA, pB = lm
    reg, sink = _registry()
    server = _server(lm, reg).start()
    try:
        prompt = _prompt(model)
        r1 = server.submit(prompt, max_new_tokens=6)
        assert wait_until(r1.done.is_set, timeout=120)
        assert list(r1.tokens) == _one_shot(model, pA, prompt, 6)

        # swap mid-flight: a long request keeps streaming across the swap
        # boundary (slots continue on the new weights — the documented
        # contract) and terminates normally
        r2 = server.submit(_prompt(model, seed=3), max_new_tokens=24)
        assert wait_until(lambda: len(r2.tokens) >= 3, timeout=120)
        ticket = server.engine.request_swap(pB, 2)
        assert ticket.done.wait(30) and ticket.ok
        assert wait_until(r2.done.is_set, timeout=120)
        assert r2.status == "done" and len(r2.tokens) == 24

        # post-swap requests serve the NEW weights, token-identically
        r3 = server.submit(prompt, max_new_tokens=6)
        assert wait_until(r3.done.is_set, timeout=120)
        assert list(r3.tokens) == _one_shot(model, pB, prompt, 6)
        assert list(r3.tokens) != list(r1.tokens)   # the weights moved

        stats = server.stats()
        assert stats["weights_step"] == 2
        assert stats["swaps"] == 1 and stats["swap_rollbacks"] == 0
        # strict guards stayed clean: same shapes -> no retrace; placed
        # arrays -> no implicit transfer
        assert stats["guard_recompiles"] == 0
        assert stats["guard_implicit_transfers"] == 0
        assert server.health()["weights_step"] == 2

        # every response is attributable to the weights that produced it
        by_id = {r["id"]: r for r in sink.of("serve_request")}
        assert by_id[r1.id]["weights_step"] == 1
        assert by_id[r3.id]["weights_step"] == 2
        assert sink.of("swap_applied")[0]["version"] == 2
        assert sink.of("swap_committed")[0]["version"] == 2
    finally:
        server.close(drain=False)


def test_swap_rejects_shape_mismatch_without_touching_weights(lm):
    import jax

    model, pA, pB = lm
    reg, _sink = _registry()
    server = _server(lm, reg)
    engine = server.engine
    bad = jax.tree.map(lambda x: x[..., :1], pB)    # every leaf truncated
    with pytest.raises(ValueError, match="shape/dtype mismatch"):
        engine.request_swap(bad, 2)
    with pytest.raises(ValueError, match="structure"):
        engine.request_swap({"nope": pB}, 2)
    assert engine.weights_step == 1 and engine._pending_swap is None
    server.close(drain=False)


def test_swap_trial_rollback_on_first_post_swap_tick_failure(lm):
    """Old params stay alive until the first post-swap tick completes: a
    failing trial tick rolls back to them, records the failure, and the
    engine keeps serving the OLD weights — a bad swap degrades the
    weights version, never availability."""
    model, pA, pB = lm
    reg, sink = _registry()
    server = _server(lm, reg)
    engine = server.engine
    prompt = _prompt(model)

    boom = {"armed": False}
    real_expire = server.queue.expire_overdue

    def expire(*a, **kw):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("injected trial-tick failure")
        return real_expire(*a, **kw)

    server.queue.expire_overdue = expire
    ticket = engine.request_swap(pB, 2)
    boom["armed"] = True
    assert engine.tick() is True        # swallowed: the loop must survive
    assert ticket.done.is_set() and ticket.ok is False
    assert ticket.stage == "tick"
    assert engine.weights_step == 1     # rolled back
    assert engine.swap_rollbacks == 1 and engine.swaps == 0
    fails = sink.of("swap_failed")
    assert fails and fails[0]["stage"] == "tick"
    rb = sink.of("swap_rollback")
    assert rb and rb[0] == {
        **rb[0], "from_version": 2, "to_version": 1,
    }
    # still serving the OLD weights, token-identically
    req = server.submit(prompt, max_new_tokens=4)
    while not req.done.is_set():
        engine.tick()
    assert list(req.tokens) == _one_shot(model, pA, prompt, 4)
    server.close(drain=False)


def test_hotswap_manager_and_http_swap_endpoint(lm, tmp_path):
    """The replica-side contract over HTTP: POST /swap to a published,
    verified step serves it (200 + weights_step everywhere); a missing or
    corrupt-at-load step answers 409, keeps the old weights serving, and
    records swap_failed + a rollback."""
    from pytorch_distributed_training_tpu.data.bpe import ByteTokenizer
    from pytorch_distributed_training_tpu.faults.inject import (
        FaultPlan,
        set_plan,
    )
    from pytorch_distributed_training_tpu.serve import (
        HotSwapManager,
        make_http_server,
        publish_params_checkpoint,
    )

    model, pA, pB = lm
    ckpt_dir = str(tmp_path / "ckpt")
    publish_params_checkpoint(ckpt_dir, 1, pA)
    reg, sink = _registry()
    server = _server(lm, reg).start()
    server.attach_hotswap(
        HotSwapManager(server, ckpt_dir, registry=reg, start_step=1)
    )
    httpd = make_http_server(server, ByteTokenizer())
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def post_swap(step):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request("POST", "/swap", body=json.dumps({"step": step}))
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        return resp.status, payload

    def healthz():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        payload = json.loads(resp.read())
        conn.close()
        return payload

    try:
        status, out = post_swap(9)              # never published
        assert status == 409 and out["ok"] is False
        assert out["stage"] == "load" and out["weights_step"] == 1

        publish_params_checkpoint(ckpt_dir, 2, pB)
        status, out = post_swap(2)
        assert status == 200 and out["ok"] is True
        assert out["weights_step"] == 2 and out["load_s"] > 0
        assert healthz()["weights_step"] == 2
        status, out = post_swap(2)              # idempotent no-op
        assert status == 200 and out.get("noop") is True

        # corrupt-at-load (the injected stand-in for a torn array that
        # verification missed): 409, old weights keep serving
        publish_params_checkpoint(ckpt_dir, 3, pA)
        prev = set_plan(FaultPlan.parse("corrupt_ckpt_swap:3"))
        try:
            status, out = post_swap(3)
        finally:
            set_plan(prev)
        assert status == 409 and out["ok"] is False
        assert "corrupt" in out["error"]
        assert out["weights_step"] == 2
        stats = server.stats()
        assert stats["swap_failures"] == 2 and stats["swap_attempts"] >= 3
        assert [r["version"] for r in sink.of("swap_failed")] == [9, 3]
        assert sink.of("swap_rollback")     # rollback recorded
        req = server.submit(_prompt(model), max_new_tokens=4)
        assert wait_until(req.done.is_set, timeout=120)
        assert req.status == "done"         # still serving, on step 2
    finally:
        httpd.shutdown()
        server.close(drain=False)


def test_hotswap_manager_watcher_polls_new_steps(lm, tmp_path):
    """Standalone-replica mode: --hotswap-poll-s semantics — the manager's
    own watcher picks a newly published verified step up with no external
    driver."""
    from pytorch_distributed_training_tpu.serve import (
        HotSwapManager,
        publish_params_checkpoint,
    )

    model, pA, pB = lm
    ckpt_dir = str(tmp_path / "ckpt")
    publish_params_checkpoint(ckpt_dir, 1, pA)
    reg, _sink = _registry()
    server = _server(lm, reg).start()
    server.attach_hotswap(
        HotSwapManager(
            server, ckpt_dir, poll_interval_s=0.05, registry=reg,
            start_step=1,
        ).start()
    )
    try:
        publish_params_checkpoint(ckpt_dir, 2, pB)
        assert wait_until(
            lambda: server.engine.weights_step == 2, timeout=60
        )
        assert server.stats()["swaps"] == 1
    finally:
        server.close(drain=False)


# =====================================================================
# THE chaos drill: corrupt publish into a loaded 2-replica fleet
# =====================================================================


def _post_generate(port, prompt, max_new, rid, timeout=120):
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": prompt, "max_new_tokens": max_new}),
            headers={"X-Request-Id": rid},
        )
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            conn.close()
            return {"outcome": "rejected", "status": resp.status}
        events = [json.loads(l) for l in resp.read().decode().splitlines()]
        conn.close()
        last = events[-1] if events else {}
        return {
            "outcome": "done" if last.get("event") == "done" else "bad",
            "events": events,
        }
    except Exception as e:          # pragma: no cover - drill diagnostics
        return {"outcome": "exception", "error": repr(e)}


def _replica_stats(port):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", "/stats")
    resp = conn.getresponse()
    payload = json.loads(resp.read())
    conn.close()
    return payload


@pytest.mark.chaos
def test_fleet_corrupt_swap_drill_zero_failures_then_converges(
    lm, tmp_path
):
    """THE acceptance drill: 2 replicas serve a closed loop while a
    corrupt checkpoint step is published — zero request failures, every
    replica records the failed swap + rollback and stays on its old
    weights, the watcher blocklists the step; a subsequently published
    good step then rolls out to BOTH replicas (router skew 0) with no
    replica restart and strict guards clean on both."""
    from pytorch_distributed_training_tpu.serve import (
        publish_params_checkpoint,
    )
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )
    from pytorch_distributed_training_tpu.serve.router import (
        RouterConfig,
        make_router_http_server,
    )

    model, pA, pB = lm
    ckpt_dir = str(tmp_path / "ckpt")
    publish_params_checkpoint(ckpt_dir, 1, pA)

    reg, sink = _registry()
    fleet = ServeFleet(
        FleetConfig(
            num_replicas=2,
            replica_args=(
                "--model", "gpt2-tiny", "--num-slots", "2",
                "--prompt-buckets", "16,32", "--max-new-tokens-cap", "64",
                "--queue-depth", "16", "--stall-timeout-s", "10",
                "--checkpoint-dir", ckpt_dir,
            ),
            # both replicas reject the load of step 2; strict guards prove
            # the swap path neither retraces nor implicitly transfers
            fault_env={0: "corrupt_ckpt_swap:2", 1: "corrupt_ckpt_swap:2"},
            replica_env={"PDT_TPU_GUARDS": "strict"},
            max_restarts=1,
            backoff_s=0.2,
            drain_timeout_s=20.0,
        ),
        RouterConfig(
            health_interval_s=0.05, health_timeout_s=1.0,
            breaker_threshold=3, breaker_cooldown_s=0.5,
            retry_backoff_s=0.02, retry_backoff_max_s=0.1,
            ttfb_timeout_s=60.0,
        ),
        registry=reg,
    ).start()
    httpd = None
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        fleet.enable_hotswap(ckpt_dir, poll_interval_s=0.1)
        httpd = make_router_http_server(fleet.router)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever, daemon=True).start()

        def wave(tag, n=6):
            results = [None] * n
            threads = []
            for i in range(n):
                def run(i=i):
                    results[i] = _post_generate(
                        port, f"{tag} request {i}", 8, f"{tag}-{i}"
                    )
                t = threading.Thread(target=run, daemon=True)
                threads.append(t)
                t.start()
            return results, threads

        # corrupt step 2 publishes while wave A is in flight
        results_a, threads_a = wave("corrupt")
        publish_params_checkpoint(ckpt_dir, 2, pB)
        for t in threads_a:
            t.join(180)
        assert all(not t.is_alive() for t in threads_a)

        # both replicas refused the swap; the rollout recorded it and the
        # watcher blocklisted the poisoned step
        assert wait_until(
            lambda: any(
                r["step"] == 2 for r in sink.of("fleet_swap")
            ),
            timeout=60,
        ), sink.records[-5:]
        rollout2 = [r for r in sink.of("fleet_swap") if r["step"] == 2][0]
        assert rollout2["failed"] == 2 and rollout2["ok"] == 0
        assert rollout2["converged"] is False
        assert wait_until(
            lambda: 2 in fleet.hotswap.watcher.blocklist, timeout=30
        )

        # ZERO request failures while the corrupt publish was rejected
        assert [r["outcome"] for r in results_a] == ["done"] * 6, results_a

        # every replica recorded the failed swap + rollback and kept its
        # old weights serving (degraded-version, still healthy)
        for replica in fleet.replicas:
            st = _replica_stats(replica.port)
            assert st["swap_failures"] >= 1, st
            assert st["weights_step"] == 1
        assert fleet.router.stats()["weights"] == {"r0": 1, "r1": 1}

        # a good step lands: the fleet converges on it — all replicas,
        # skew zero, NO replica restarted, no retrace/transfer violation
        publish_params_checkpoint(ckpt_dir, 3, pB)
        assert wait_until(
            lambda: fleet.router.stats()["weights"] == {"r0": 3, "r1": 3}
            and fleet.router.stats()["version_skew"] == 0,
            timeout=120,
        ), fleet.router.stats()
        rollout3 = [r for r in sink.of("fleet_swap") if r["step"] == 3][0]
        assert rollout3["ok"] == 2 and rollout3["converged"] is True
        for replica in fleet.replicas:
            d = replica.describe()
            assert d["spawns"] == 1 and d["restarts_used"] == 0, d
            st = _replica_stats(replica.port)
            assert st["weights_step"] == 3
            assert st["guard_mode"] == "strict"
            assert st["guard_recompiles"] == 0
            assert st["guard_implicit_transfers"] == 0
            assert st["swaps"] >= 1 and st["swap_rollbacks"] == 0

        # the converged pool still answers, on the new weights
        results_b, threads_b = wave("post", n=4)
        for t in threads_b:
            t.join(180)
        assert [r["outcome"] for r in results_b] == ["done"] * 4, results_b
        reqs = [
            r for r in sink.of("router_request")
            if r["id"].startswith("post-")
        ]
        assert reqs and all(r["weights_step"] == 3 for r in reqs)
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.stop(drain=False)

    # the drill's stream folds into the summarize_metrics swap section
    import subprocess
    import sys

    stream = str(tmp_path / "metrics.jsonl")
    with open(stream, "w") as f:
        for r in sink.records:
            f.write(json.dumps(r) + "\n")
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", stream, "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(proc.stdout)
    swap = summary["swap"]
    assert swap["admitted"] >= 2
    assert swap["rollouts"] == 2 and swap["rollouts_converged"] == 1
    assert swap["blocklisted"] == [2]
    assert swap["skew_events"] >= 1
    table = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", stream],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert table.returncode == 0 and "hotswap:" in table.stdout
