"""Speculative decoding + chunked prefill tests (serve/engine.py verify
dispatch, serve/sampling.py ``spec_accept``, serve/paged_cache.py
reservation overshoot, ops/paged_attention.py multi-token query path and
their engine integration): acceptance bit-identity against the
non-speculative stream (greedy AND fixed-seed, host vs device sampler),
adversarial all-reject rollback with exact allocator accounting, chunked
prefill token-identity across ragged chunk boundaries, mixed spec/non-spec
slots in one tick, the page-reservation overshoot formula, and the strict
tick-wide scope with the verify program's collective manifest. CPU, tier-1
(except the perf-marked BENCH_spec gate).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.paged_cache import PageAllocator
from pytorch_distributed_training_tpu.serve.sampling import (
    device_sample,
    spec_accept,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = pytest.mark.serve

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


def _want(model, params, prompts, T):
    return [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]


def _run_server(model, params, prompts, T, *, temperature=0.0, top_k=0,
                seed=0, spec_flags=None, draft_model=None, draft_params=None,
                mutate_engine=None, kv_layout="paged", sampling="device",
                **cfg_kw):
    reg, sink = _registry()
    cfg_kw.setdefault("prompt_buckets", (4, 8, 16))
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, max_new_tokens=T,
            kv_layout=kv_layout, sampling=sampling, **cfg_kw,
        ),
        queue_depth=16, registry=reg,
        draft_model=draft_model, draft_params=draft_params,
    )
    if mutate_engine is not None:
        mutate_engine(server.engine)
    server.start()
    try:
        reqs = [
            server.submit(
                p, max_new_tokens=T, temperature=temperature, top_k=top_k,
                seed=seed + i,
                spec=None if spec_flags is None else spec_flags[i],
            )
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs), [r.status for r in reqs]
    toks = [np.asarray(r.tokens, np.int32) for r in reqs]
    return toks, server.stats(), reg, sink


# --------------------------------------------------- acceptance sampling


def test_spec_accept_leading_match_semantics():
    """``spec_accept`` commits exactly the leading run of draft tokens that
    match the per-position streams, and every target row equals what
    ``device_sample`` produces for that (seed, step) — the primitive the
    engine's bit-identity rests on."""
    rng = np.random.default_rng(0)
    S, Q, V = 3, 4, 32
    logits = jnp.asarray(rng.normal(size=(S, Q, V)), jnp.float32)
    seeds = jnp.asarray([5, 6, 7], jnp.int32)
    steps0 = jnp.asarray([1, 3, 9], jnp.int32)
    temps = jnp.asarray([0.0, 0.7, 0.0], jnp.float32)
    top_ks = jnp.asarray([0, 4, 0], jnp.int32)

    # the per-position reference: each row sampled on its own stream
    want = np.stack([
        np.asarray(device_sample(
            logits[:, j, :], seeds, steps0 + j, temps, top_ks
        ))
        for j in range(Q)
    ], axis=1)

    # drafts agreeing on a known leading prefix per slot: 3, 0, 1 matches.
    # draft[j] guesses emission j (= target row j): the engine feeds it as
    # token j+1, so row j+1's logits condition on it — accept stops at the
    # first row whose guess missed.
    draft = want[:, : Q - 1].copy()
    draft[1, 0] = (draft[1, 0] + 1) % V
    draft[2, 1] = (draft[2, 1] + 1) % V
    target, accept = spec_accept(
        logits, jnp.asarray(draft), seeds, steps0, temps, top_ks
    )
    np.testing.assert_array_equal(np.asarray(target), want)
    np.testing.assert_array_equal(np.asarray(accept), [3, 0, 1])


# ------------------------------------------------------ stream identity


def test_spec_greedy_bit_identical_to_generate(lm):
    """Acceptance pin: the speculative engine's greedy streams (n-gram
    self-drafting) are bit-identical to one-shot generate(), and the
    speculation telemetry fires."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = _want(model, params, prompts, T)
    toks, stats, reg, _ = _run_server(
        model, params, prompts, T, spec_k=3,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
    assert stats["spec_k"] == 3 and stats["spec_draft"] == "ngram"
    assert stats["spec_dispatches"] > 0
    assert 0 < stats["spec_accepted"] <= stats["spec_drafted"]
    assert 0.0 < stats["spec_accept_rate"] <= 1.0
    # one verify dispatch commits more than one token on average
    assert stats["tokens_per_dispatch"] > 1.0
    gauges = reg.snapshot()["gauges"]
    assert "serve/spec_accept_rate" in gauges
    assert "serve/tokens_per_dispatch" in gauges


def test_spec_fixed_seed_sampled_identical_to_host_sampler(lm):
    """Fixed-seed sampled decode is exact across speculation AND the
    sampler location: spec paged+device == non-spec dense+host, token for
    token — the ``fold_in(key(seed), step)`` contract extended to the
    k+1-position verify block."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 7, 12], seed=3)
    kw = dict(temperature=0.8, top_k=5, seed=11)
    spec_toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=3, **kw
    )
    host_toks, _, _, _ = _run_server(
        model, params, prompts, T, kv_layout="dense", sampling="host", **kw
    )
    assert stats["spec_dispatches"] > 0
    for i, (s, h) in enumerate(zip(spec_toks, host_toks)):
        assert len(s) == T
        np.testing.assert_array_equal(s, h, err_msg=f"request {i}")


def test_mixed_spec_and_nonspec_slots_share_ticks(lm):
    """Per-request spec opt-out: slots with ``spec=False`` ride the same
    verify dispatch with zero drafted tokens, and every stream — both
    kinds, interleaved in the same ticks — stays greedy-exact."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=3,
        spec_flags=[True, False, True, False, None],
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
    # non-spec slots drafted nothing, spec slots did
    assert 0 < stats["spec_drafted"] < stats["spec_dispatches"] * 2 * 3


# ------------------------------------------------- rollback + allocator


def test_all_reject_rollback_releases_every_page(lm):
    """Adversarial drafter: every proposal is -1 (matches no sampled token
    ever), so EVERY tick rejects the whole draft block. The streams must
    still be greedy-exact (row 0 of each verify is correct by
    construction), the engine must still make one token of progress per
    dispatch, and rollback must be pure cursor rewind: zero accepted
    drafts, zero page_exhausted, and every page back in the pool."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = _want(model, params, prompts, T)

    def sabotage(engine):
        engine._ngram_draft = lambda hist, k: [-1] * k

    toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=3, mutate_engine=sabotage,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
        assert len(got) == T
    assert stats["spec_dispatches"] > 0 and stats["spec_drafted"] > 0
    assert stats["spec_accepted"] == 0
    assert stats["spec_accept_rate"] == 0.0
    # all-reject degrades to the non-speculative rate: at most ONE token
    # per SLOT per dispatch (the cross-slot batch still shares a dispatch)
    assert 1.0 <= stats["tokens_per_dispatch"] <= 2.0
    assert stats["page_exhausted"] == 0
    # the dead draft lanes leaked nothing: pool exactly restored
    assert stats["kv_pages_used"] == 0
    assert stats["kv_pages_free"] == stats["kv_pages_total"]


# ------------------------------------------------------- chunked prefill


def test_chunked_prefill_identical_across_ragged_boundaries(lm):
    """Chunked prefill == monolithic prefill, token for token, across
    prompt lengths that land on, under, and over the chunk boundary (len %
    chunk in {0,1,chunk-1}) — the ragged last chunk pads but commits only
    real positions."""
    model, params = lm
    T = 5
    lengths = [3, 4, 5, 8, 9, 14, 16]
    prompts = _prompts(model, lengths, seed=5)
    want = _want(model, params, prompts, T)
    toks, stats, reg, _ = _run_server(
        model, params, prompts, T, prefill_chunk=4,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(
            got, ref, err_msg=f"request {i} (len {lengths[i]})"
        )
    assert stats["prefill_chunk"] == 4
    assert stats["prefill_chunks"] == sum(-(-n // 4) for n in lengths)
    assert "serve/prefill_chunks" in reg.snapshot()["gauges"]


def test_spec_plus_chunked_prefill_identical(lm):
    """Both features on at once: chunked prompts stream in while other
    slots verify speculative blocks, and every stream is still exact."""
    model, params = lm
    T = 5
    prompts = _prompts(model, [3, 9, 14, 16, 5], seed=2)
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=3, prefill_chunk=4,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
    assert stats["spec_dispatches"] > 0 and stats["prefill_chunks"] > 0


# ----------------------------------------------------- draft-model lane


def test_draft_model_lane_identity_and_acceptance(lm):
    """The draft-model lane with the BASE model drafting for itself:
    greedy proposals then match the greedy target stream almost always
    (the first verify after a partial acceptance may resync), acceptance
    approaches 1.0, and the streams stay exact."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = _want(model, params, prompts, T)
    toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=3, spec_draft="model",
        draft_model=model, draft_params=params,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
    assert stats["spec_draft"] == "model"
    assert stats["spec_accept_rate"] > 0.9, stats["spec_accept_rate"]


# ------------------------------------------------ reservation overshoot


def test_pages_reserved_formula():
    """The documented overshoot formula: a spec slot's reservation covers
    the highest position a verify dispatch can ever scatter —
    ``(prompt + max_new - 2) + k`` — for any shape, so mid-flight
    page exhaustion is impossible by construction."""
    alloc = PageAllocator(
        num_pages=64, page_size=4, pages_per_slot=16, num_slots=1
    )
    assert alloc.pages_reserved(10, 0) == alloc.pages_needed(10)
    for total, k, page in [(5, 1, 2), (8, 3, 4), (17, 7, 4), (40, 5, 8),
                           (3, 2, 16), (64, 3, 8)]:
        a = PageAllocator(
            num_pages=128, page_size=page, pages_per_slot=64, num_slots=1
        )
        reserved = a.pages_reserved(total, k)
        assert reserved == a.pages_needed(total + k)
        worst_scatter_index = (total - 2) + k
        assert worst_scatter_index < reserved * page, (total, k, page)


def test_reservation_overshoot_never_trips_page_exhausted(lm):
    """A pool sized EXACTLY to the formula (num_slots x
    pages_reserved(bucket + max_new, k) + the null page) serves a burst of
    full-length speculative requests with ZERO page_exhausted events —
    the overshoot reservation makes draft scatter beyond the emission cap
    safe by construction, not by slack."""
    model, params = lm
    T, k, page_size = 8, 3, 4
    prompts = _prompts(model, [8, 5, 8, 6, 7, 8], seed=1)
    want = _want(model, params, prompts, T)
    per_slot = -(-(8 + T + k) // page_size)     # pages_reserved(16, 3)
    num_pages = 2 * per_slot + 1                # 2 slots, + null page
    toks, stats, _, _ = _run_server(
        model, params, prompts, T, spec_k=k,
        prompt_buckets=(8,), page_size=page_size, num_pages=num_pages,
    )
    for i, (got, ref) in enumerate(zip(toks, want)):
        np.testing.assert_array_equal(got, ref, err_msg=f"request {i}")
        assert len(got) == T                    # ran to the emission cap
    assert stats["page_exhausted"] == 0
    assert stats["kv_pages_used"] == 0
    assert stats["kv_pages_free"] == num_pages - 1


# ------------------------------------------------- strict scope + audits


def test_spec_strict_scope_verify_manifest_and_donation(lm):
    """With warmup, a speculative session runs its whole tick under
    transfer_guard("disallow"): zero implicit transfers (the only D2H is
    the verify result — token ids + accept counts), zero recompiles, the
    hot verify program passes its zero-collective manifest, and its cache
    donation survived to the executable."""
    from pytorch_distributed_training_tpu.analysis.guards import GuardSet

    model, params = lm
    reg, sink = _registry()
    gs = GuardSet(mode="strict", registry=reg)
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8), max_new_tokens=4,
            kv_layout="paged", sampling="device", warmup=True, spec_k=3,
        ),
        queue_depth=16, registry=reg, guards=gs,
    ).start()
    try:
        rng = np.random.default_rng(3)
        reqs = []
        for i, n in enumerate([3, 6, 2, 7, 4, 5]):
            reqs.append(server.submit(
                rng.integers(1, model.config.vocab_size, n).astype(np.int32),
                max_new_tokens=4,
                temperature=0.8 if i % 2 else 0.0, top_k=3, seed=i,
            ))
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()

    assert all(r.status == "done" for r in reqs)
    stats = server.stats()
    assert stats["guard_mode"] == "strict"
    assert stats["guard_recompiles"] == 0
    assert stats["guard_implicit_transfers"] == 0
    assert not sink.of("recompile") and not sink.of("implicit_transfer")
    assert gs.wrapped["serve_verify"].calls >= 2
    # the hot program under speculation is the VERIFY dispatch: it carries
    # the zero-collective manifest (single-device engine moves zero bytes)
    (comm,) = sink.of("comm_audit")
    assert comm["name"] == "serve_verify" and comm["ok"] is True
    assert comm["count"] == 0
    # cache donation on the verify program survived lowering
    donations = [
        r for r in sink.of("donation_audit") if r["name"] == "serve_verify"
    ]
    assert donations and all(r.get("aliased") for r in donations)


# --------------------------------------------------------- summarization


def test_summarize_metrics_speculation_line():
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        from summarize_metrics import (
            render_serve_table,
            summarize_serve,
            summarize_spec,
        )
    finally:
        sys.path.pop(0)

    records = [
        {"record": "serve_request", "status": "done", "bucket": 8,
         "new_tokens": 4, "ttft_s": 0.01, "tpot_s": 0.002, "total_s": 0.02,
         "queue_wait_s": 0.001, "ts": 100.0},
        {"record": "serve_summary", "kv_layout": "paged", "sampling":
         "device", "kv_page_size": 8, "kv_pages_total": 32, "kv_pages_peak":
         6, "page_exhausted": 0, "spec_k": 3, "spec_draft": "ngram",
         "spec_dispatches": 10, "spec_drafted": 30, "spec_accepted": 21,
         "spec_accept_rate": 0.7, "tokens_per_dispatch": 3.1,
         "prefill_chunk": 4, "prefill_chunks": 9},
    ]
    spec = summarize_spec(records)
    assert spec["spec_k"] == 3 and spec["accept_rate"] == 0.7
    assert spec["prefill_chunks"] == 9
    table = render_serve_table(summarize_serve(records))
    assert "speculation:" in table
    assert "accept-rate=0.700" in table
    assert "tokens/dispatch=3.10" in table
    assert "prefill-chunk=4" in table
    # engines without speculation keep the old table
    assert summarize_spec([records[0]]) is None


# ------------------------------------------------------------ perf gate


@pytest.mark.perf
def test_spec_bench_tpot_gate(tmp_path):
    """bench.py --spec: speculation must cut p50 TPOT by >= 2x against the
    non-speculative paged baseline on the CPU quick bench, with all four
    variants (spec on/off x chunked on/off) emitting BIT-IDENTICAL token
    streams and zero page exhaustion — the PR's perf acceptance gate."""
    out = tmp_path / "BENCH_spec.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--spec", "--spec-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    assert result["streams_identical"] is True, result["stream_digests"]
    assert result["tpot_speedup"] >= 2.0, result["tpot_speedup"]
    spec = result["spec"]
    assert spec["spec_k"] > 0 and 0 < spec["spec_accept_rate"] <= 1.0
    assert spec["tokens_per_dispatch"] > result["baseline"][
        "tokens_per_dispatch"
    ]
    for name in ("baseline", "spec", "chunked", "spec_chunked"):
        v = result[name]
        assert v["page_exhausted"] == 0, name
        assert v["buckets"], name
        for b in v["buckets"]:
            assert b["ttft_s"]["count"] > 0 and b["tpot_s"]["count"] > 0
    assert result["chunked"]["prefill_chunks"] > 0
    assert result["spec_chunked"]["prefill_chunks"] > 0
