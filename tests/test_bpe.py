"""Byte-level BPE tests: parity with transformers' GPT2Tokenizer over the
same vocab/merges files (built as a tiny fixture — no network), plus the
byte-alphabet invariants and the offline byte fallback.
"""

import json

import numpy as np
import pytest

from pytorch_distributed_training_tpu.data.bpe import (
    ByteLevelBPETokenizer,
    ByteTokenizer,
    bytes_to_unicode,
    encode_lm_rows,
)

SAMPLES = [
    "Hello world!",
    "The quick brown fox jumps over the lazy dog.",
    "it's we've they'll I'm don't",
    "  spaced   out\ttabs\nnewlines  ",
    "numbers 12345 and mixed a1b2",
    "unicode: café naïve über — dash",
    "",
]


def _byte_vocab_fixture(tmp_path):
    """A real (if tiny) GPT-2-format vocab: all 256 byte symbols + a few
    merges + <|endoftext|>. Every text is encodable (byte fallback through
    the alphabet), and the merges exercise the rank loop."""
    b2u = bytes_to_unicode()
    symbols = [b2u[i] for i in range(256)]
    merges = [
        (b2u[ord("t")], b2u[ord("h")]),             # th
        (b2u[ord("t")] + b2u[ord("h")], b2u[ord("e")]),  # the
        (b2u[ord(" ")], b2u[ord("t")] + b2u[ord("h")] + b2u[ord("e")]),  # Ġthe
        (b2u[ord("e")], b2u[ord("r")]),             # er
        (b2u[ord("o")], b2u[ord("v")]),             # ov
        (b2u[ord("o")] + b2u[ord("v")], b2u[ord("e")] + b2u[ord("r")]),  # over
    ]
    vocab = {s: i for i, s in enumerate(symbols)}
    for a, b in merges:
        vocab.setdefault(a + b, len(vocab))
    vocab["<|endoftext|>"] = len(vocab)
    vp = tmp_path / "encoder.json"
    mp = tmp_path / "merges.txt"
    vp.write_text(json.dumps(vocab), encoding="utf-8")
    mp.write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges) + "\n",
        encoding="utf-8",
    )
    return str(vp), str(mp)


def test_bytes_to_unicode_invariants():
    m = bytes_to_unicode()
    assert len(m) == 256 and len(set(m.values())) == 256
    assert m[ord("A")] == "A"  # printable ascii maps to itself


def test_parity_with_transformers(tmp_path):
    transformers = pytest.importorskip("transformers")
    vp, mp = _byte_vocab_fixture(tmp_path)
    ours = ByteLevelBPETokenizer(vp, mp)
    theirs = transformers.GPT2Tokenizer(vocab_file=vp, merges_file=mp)
    for text in SAMPLES:
        assert ours.text_ids(text) == theirs.encode(text), text


def test_roundtrip_decode(tmp_path):
    vp, mp = _byte_vocab_fixture(tmp_path)
    tok = ByteLevelBPETokenizer(vp, mp)
    for text in SAMPLES:
        assert tok.decode(tok.text_ids(text)) == text


def test_merges_actually_merge(tmp_path):
    vp, mp = _byte_vocab_fixture(tmp_path)
    tok = ByteLevelBPETokenizer(vp, mp)
    ids = tok.text_ids("the theater")
    # "the" must encode via the Ġthe/the merges, not byte-by-byte
    assert len(ids) < len("the theater")


def test_byte_fallback_roundtrip():
    tok = ByteTokenizer()
    for text in SAMPLES:
        ids = tok.text_ids(text)
        assert all(0 <= i < 256 for i in ids)
        assert tok.decode(ids) == text


def test_encode_lm_rows_shapes(tmp_path):
    vp, mp = _byte_vocab_fixture(tmp_path)
    tok = ByteLevelBPETokenizer(vp, mp)
    out = encode_lm_rows(tok, ["the fox", "a much longer text " * 20], 16)
    assert out["input_ids"].shape == (2, 16)
    assert out["attention_mask"].shape == (2, 16)
    # row 0: ends with eot, padded with pad_id, mask matches
    n0 = out["attention_mask"][0].sum()
    assert out["input_ids"][0, n0 - 1] == tok.eot_id
    assert (out["input_ids"][0, n0:] == tok.pad_id).all()
    # row 1: truncated to full length
    assert out["attention_mask"][1].sum() == 16
    np.testing.assert_array_equal(
        out["input_ids"][1], encode_lm_rows(tok, ["a much longer text " * 20], 16)["input_ids"][0]
    )


def test_re_fallback_pattern_consumes_every_char():
    """The `re`-module fallback pre-tokenizer must consume ALL input
    characters (findall dropping any breaks the lossless decode contract).
    Regression: '_' matched no alternative (it is \\w but not [^\\W\\d_])."""
    import re

    from pytorch_distributed_training_tpu.data.bpe import _GPT2_PAT_RE

    pat = re.compile(_GPT2_PAT_RE)
    for text in SAMPLES + ["a_b", "_leading", "trailing_", "__dunder__ x_1"]:
        assert "".join(pat.findall(text)) == text


def test_re_fallback_roundtrip(tmp_path, monkeypatch):
    """Force the fallback pattern through the real tokenizer and round-trip."""
    import re

    import pytorch_distributed_training_tpu.data.bpe as bpe_mod

    vp, mp = _byte_vocab_fixture(tmp_path)
    tok = ByteLevelBPETokenizer(vp, mp)
    monkeypatch.setattr(bpe_mod, "_PRETOK", re.compile(bpe_mod._GPT2_PAT_RE))
    for text in SAMPLES + ["snake_case_name", "_x __y"]:
        assert tok.decode(tok.text_ids(text)) == text
