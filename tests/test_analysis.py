"""Static linter tests (analysis/lint.py + rules/): one positive and one
negative fixture per rule so rule regressions are caught, waiver-file
mechanics, and the repo-lints-clean gate that mirrors
``scripts/lint.py --check``. CPU-only, tier-1."""

import os
import textwrap

import pytest

from pytorch_distributed_training_tpu.analysis.lint import (
    DEFAULT_WAIVERS,
    REPO_ROOT,
    lint_paths,
    lint_source,
    summary_record,
)
from pytorch_distributed_training_tpu.analysis.waivers import (
    load_waivers,
    parse_waivers_toml,
)

pytestmark = pytest.mark.lint


def rules_of(src):
    return [f.rule for f in lint_source(textwrap.dedent(src))]


# ------------------------------------------------------------ traced-branch


def test_traced_branch_flags_if_on_tracer():
    src = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """
    assert rules_of(src) == ["traced-branch"]


def test_traced_branch_flags_fn_passed_to_jit_and_while():
    src = """
    import jax

    def step(state, batch):
        y = state + batch
        while y < 3:
            y = y + 1
        return y

    step_j = jax.jit(step, donate_argnums=(0,))
    """
    assert rules_of(src) == ["traced-branch"]


def test_traced_branch_flags_range_over_tracer():
    src = """
    import jax

    @jax.jit
    def f(n, x):
        for _ in range(n):
            x = x + 1
        return x
    """
    assert rules_of(src) == ["traced-branch"]


def test_traced_branch_negative_static_and_host():
    src = """
    import jax

    @jax.jit
    def f(x, mask=None):
        if mask is None:              # None-check: static under trace
            mask = x * 0
        if x.ndim == 2:               # shape guard: static under trace
            x = x[None]
        for leaf in jax.tree.leaves({"a": x}):   # container iteration: fine
            mask = mask + leaf
        return mask

    def host(flag, items):
        if flag:                      # not traced at all
            return [i for i in items]
        return []
    """
    assert rules_of(src) == []


def test_traced_branch_factory_closure_is_static():
    """A jit FACTORY's params are trace-time constants: branching on them
    inside the returned (traced) step is legal."""
    src = """
    import jax

    def make_step(log_extra):
        def step(state, batch):
            out = state + batch
            if log_extra:
                out = out * 2
            return out
        return jax.jit(step, donate_argnums=(0,))
    """
    assert rules_of(src) == []


# -------------------------------------------------------------- impure-call


def test_impure_call_flags_time_and_np_random():
    src = """
    import time
    import numpy as np
    import jax

    @jax.jit
    def f(x):
        t = time.time()
        noise = np.random.normal(size=3)
        return x + t + noise
    """
    assert rules_of(src).count("impure-call") == 2


def test_impure_call_negative_host_and_jax_random():
    src = """
    import time
    import jax

    def host_loop():
        return time.time()

    @jax.jit
    def f(x, key):
        return x + jax.random.normal(key, x.shape)
    """
    assert "impure-call" not in rules_of(src)


# ------------------------------------------------------ host-transfer-traced


def test_host_transfer_flags_device_get_and_item_in_traced():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def f(x):
        host = jax.device_get(x)
        v = x.item()
        return np.asarray(x) + host + v
    """
    assert rules_of(src).count("host-transfer-traced") == 3


def test_host_transfer_negative_untraced():
    src = """
    import jax
    import numpy as np

    def host(x):
        return float(np.asarray(jax.device_get(x)).mean())
    """
    assert "host-transfer-traced" not in rules_of(src)


# --------------------------------------------------------- host-sync-in-loop


def _lint_named(src, relpath):
    return [
        f.rule for f in lint_source(textwrap.dedent(src), path=relpath)
    ]


def test_host_sync_in_loop_flags_train_subsystem():
    src = """
    import jax

    def epoch_loop(batches, step, state):
        for b in batches:
            state, loss = step(state, b)
            print(float(jax.device_get(loss)))
        return state
    """
    rules = _lint_named(src, "pytorch_distributed_training_tpu/train/x.py")
    assert rules == ["host-sync-in-loop"]


def test_host_sync_in_loop_ignores_other_subsystems():
    src = """
    import jax

    def epoch_loop(batches, step, state):
        for b in batches:
            state, loss = step(state, b)
            print(float(jax.device_get(loss)))
        return state
    """
    assert _lint_named(
        src, "pytorch_distributed_training_tpu/data/x.py"
    ) == []


# ----------------------------------------------------------- missing-donation


def test_missing_donation_flags_state_rewriter():
    src = """
    import jax

    def step(state, batch):
        new_state = state.apply_gradients(batch)
        return new_state

    step_j = jax.jit(step)
    """
    assert "missing-donation" in rules_of(src)


def test_missing_donation_flags_through_vmap():
    src = """
    import jax
    import jax.numpy as jnp

    def one(cache, tok):
        new_cache = jax.tree.map(lambda c: c + tok, cache)
        return new_cache

    decode = jax.jit(jax.vmap(one, in_axes=(0, 0)))
    """
    assert "missing-donation" in rules_of(src)


def test_missing_donation_negative_donated_or_pure():
    src = """
    import jax

    def step(state, batch):
        new_state = state.apply_gradients(batch)
        return new_state

    def metric(state, batch):
        return (state * batch).sum()

    step_j = jax.jit(step, donate_argnums=(0,))
    metric_j = jax.jit(metric)
    """
    assert "missing-donation" not in rules_of(src)


# ---------------------------------------------------------------- prng-reuse


def test_prng_reuse_flags_double_draw():
    src = """
    import jax

    def f(seed, shape):
        key = jax.random.key(seed)
        a = jax.random.normal(key, shape)
        b = jax.random.uniform(key, shape)
        return a + b
    """
    assert rules_of(src) == ["prng-reuse"]


def test_prng_reuse_negative_split_and_fold():
    src = """
    import jax

    def f(seed, shape):
        key = jax.random.key(seed)
        a_key, b_key = jax.random.split(key)
        a = jax.random.normal(a_key, shape)
        key = jax.random.fold_in(b_key, 1)     # rebind: fresh key
        b = jax.random.uniform(key, shape)
        c = jax.random.fold_in(key, 2)         # deriving, not consuming
        return a + b, c
    """
    assert rules_of(src) == []


# ------------------------------------------------------------ mutable-default


def test_mutable_default_flags_list_dict():
    src = """
    def f(x, acc=[], opts={}):
        acc.append(x)
        return acc, opts
    """
    assert rules_of(src) == ["mutable-default", "mutable-default"]


def test_mutable_default_negative():
    src = """
    def f(x, acc=None, shape=(1, 2)):
        return acc or [x], shape
    """
    assert rules_of(src) == []


# -------------------------------------------------------------------- waivers


def test_waiver_parse_match_and_errors(tmp_path):
    text = textwrap.dedent("""
    # comment
    [[waiver]]
    rule = "prng-reuse"
    file = "pkg/sub/*.py"
    symbol = "Klass.method"
    reason = "keys are per-request streams"
    """)
    (w,) = parse_waivers_toml(text)
    assert w.rule == "prng-reuse" and w.symbol == "Klass.method"

    from pytorch_distributed_training_tpu.analysis.rules.common import (
        Finding,
    )

    hit = Finding("prng-reuse", "pkg/sub/mod.py", 1, 0,
                  "Klass.method.inner", "m")
    miss_rule = Finding("impure-call", "pkg/sub/mod.py", 1, 0,
                        "Klass.method", "m")
    miss_sym = Finding("prng-reuse", "pkg/sub/mod.py", 1, 0,
                       "Klass.methodical", "m")
    assert w.matches(hit)
    assert not w.matches(miss_rule)
    assert not w.matches(miss_sym)

    with pytest.raises(ValueError, match="missing"):
        parse_waivers_toml('[[waiver]]\nrule = "x"\nfile = "y"')
    with pytest.raises(ValueError, match="unsupported waiver syntax"):
        parse_waivers_toml("[[waiver]]\nrule = [1, 2]")
    with pytest.raises(ValueError, match="outside"):
        parse_waivers_toml('rule = "x"')


def test_lint_paths_applies_waivers_and_reports_unused(tmp_path):
    bad = tmp_path / "train" / "hot.py"
    bad.parent.mkdir()
    bad.write_text(textwrap.dedent("""
        import jax

        def loop(batches, state, step):
            for b in batches:
                state = step(state, b)
                print(jax.device_get(state))
            return state
    """))
    report = lint_paths([str(tmp_path)])
    assert [f.rule for f in report.findings] == ["host-sync-in-loop"]
    assert not report.clean

    waivers = parse_waivers_toml(textwrap.dedent("""
        [[waiver]]
        rule = "host-sync-in-loop"
        file = "*train/hot.py"
        reason = "test fixture"

        [[waiver]]
        rule = "impure-call"
        file = "nowhere/*.py"
        reason = "dead entry"
    """))
    report = lint_paths([str(tmp_path)], waivers)
    assert report.clean and len(report.waived) == 1
    assert [w.rule for w in report.unused_waivers] == ["impure-call"]

    rec = summary_record(report)
    assert rec["record"] == "lint_summary"
    assert rec["findings"] == 0 and rec["waived"] == 1
    assert rec["unused_waivers"] == 1 and rec["clean"]


def test_lint_reports_parse_errors(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    report = lint_paths([str(tmp_path)])
    assert not report.clean and "broken.py" in report.errors[0]


# -------------------------------------------------------------- the repo gate


def test_repo_lints_clean():
    """The acceptance gate: the whole package lints clean modulo the
    documented waivers — and no waiver has rotted into uselessness. This
    is ``scripts/lint.py --check`` as a tier-1 test."""
    package = os.path.join(REPO_ROOT, "pytorch_distributed_training_tpu")
    report = lint_paths([package], load_waivers(DEFAULT_WAIVERS))
    assert report.errors == []
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
    assert report.unused_waivers == [], [
        (w.rule, w.file, w.symbol) for w in report.unused_waivers
    ]


def test_lint_cli_check(tmp_path, capsys):
    """scripts/lint.py --check: exit 0 on the real tree, 1 on a dirty one,
    and --metrics-dir writes a lint_summary record."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_cli", os.path.join(REPO_ROOT, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    mdir = str(tmp_path / "metrics")
    assert mod.main(["--check", "--metrics-dir", mdir]) == 0
    capsys.readouterr()
    import json

    with open(os.path.join(mdir, "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    assert any(r.get("record") == "lint_summary" for r in recs)

    dirty = tmp_path / "serve" / "bad.py"
    dirty.parent.mkdir()
    dirty.write_text(
        "import jax\n"
        "def loop(xs, s, step):\n"
        "    for x in xs:\n"
        "        s = step(s, x)\n"
        "        jax.device_get(s)\n"
        "    return s\n"
    )
    assert mod.main(["--check", str(tmp_path / "serve")]) == 1
    capsys.readouterr()
