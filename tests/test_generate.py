"""Generation tests: KV-cache decode parity against a no-cache reference
loop, per-row prompt-length handling, eot freezing, sampling determinism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.utils.config import model_preset


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params


def _greedy_no_cache(model, params, prompt, steps):
    """Reference loop: full forward over the growing prefix each step."""
    ids = np.asarray(prompt).copy()
    for _ in range(steps):
        logits = model.apply({"params": params}, jnp.asarray(ids))
        nxt = np.argmax(np.asarray(logits)[:, -1, :], axis=-1)
        ids = np.concatenate([ids, nxt[:, None].astype(np.int32)], axis=1)
    return ids


def test_greedy_cache_matches_no_cache(lm):
    model, params = lm
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, model.config.vocab_size, (3, 7)).astype(np.int32)
    want = _greedy_no_cache(model, params, prompt, 6)
    got = generate(model, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_padded_rows_first_token(lm):
    """Each row's first sampled token must come from its own last REAL
    prompt position, with pad tokens invisible to attention."""
    model, params = lm
    rng = np.random.default_rng(1)
    lengths = np.array([4, 7], np.int32)
    prompt = np.zeros((2, 7), np.int32)
    for i, n in enumerate(lengths):
        prompt[i, :n] = rng.integers(1, model.config.vocab_size, n)
    got = generate(
        model, params, prompt, max_new_tokens=1, prompt_lengths=lengths
    )
    for i, n in enumerate(lengths):
        row = prompt[i : i + 1, :n]
        logits = model.apply({"params": params}, jnp.asarray(row))
        want = int(np.argmax(np.asarray(logits)[0, -1, :]))
        assert int(got[i, 7]) == want, f"row {i}"


def test_padded_matches_exact_per_row(lm):
    """Right-padding positional-gap fix: every row of a ragged padded batch
    generates EXACTLY what it generates alone unpadded — decode steps thread
    per-row position offsets (prompt_lengths + t), so a short row no longer
    sees a positional jump at the padded column index."""
    model, params = lm
    rng = np.random.default_rng(5)
    lengths = np.array([3, 7, 5], np.int32)
    P, T = 7, 6
    prompt = np.zeros((3, P), np.int32)
    for i, n in enumerate(lengths):
        prompt[i, :n] = rng.integers(1, model.config.vocab_size, n)
    out = np.asarray(generate(
        model, params, prompt, max_new_tokens=T, prompt_lengths=lengths
    ))
    for i, n in enumerate(lengths):
        exact = np.asarray(
            generate(model, params, prompt[i : i + 1, :n], max_new_tokens=T)
        )[0, n:]
        np.testing.assert_array_equal(
            out[i, P:], exact, err_msg=f"row {i} (len {n})"
        )


def test_eot_freeze(lm):
    model, params = lm
    rng = np.random.default_rng(2)
    prompt = rng.integers(1, model.config.vocab_size, (1, 5)).astype(np.int32)
    free = generate(model, params, prompt, max_new_tokens=5)
    eot = int(free[0, 5])  # make the first generated token the stop token
    frozen = generate(model, params, prompt, max_new_tokens=5, eot_id=eot)
    assert (np.asarray(frozen)[0, 5:] == eot).all()


def test_sampling_deterministic_per_key(lm):
    model, params = lm
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, model.config.vocab_size, (2, 6)).astype(np.int32)
    a = generate(model, params, prompt, max_new_tokens=4, temperature=0.8,
                 top_k=8, rng=jax.random.key(7))
    b = generate(model, params, prompt, max_new_tokens=4, temperature=0.8,
                 top_k=8, rng=jax.random.key(7))
    c = generate(model, params, prompt, max_new_tokens=4, temperature=0.8,
                 top_k=8, rng=jax.random.key(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    # sampled ids stay in-vocab
    assert (np.asarray(a) < model.config.vocab_size).all()


def test_rejects_non_causal(lm):
    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )

    enc = BertForSequenceClassification(model_preset("tiny"))
    with pytest.raises(ValueError, match="causal"):
        generate(enc, {}, np.ones((1, 4), np.int32), max_new_tokens=1)


def test_relayout_roundtrip(lm):
    """unstack(stacked) -> stack -> identical pytree (scanned <-> per-layer
    layouts hold the same weights)."""
    import dataclasses

    from pytorch_distributed_training_tpu.models.relayout import (
        stack_layer_params,
        unstack_scanned_params,
    )

    model, _ = lm
    scanned = GPT2LMModel(dataclasses.replace(model.config, scan_layers=True))
    sp = scanned.init(jax.random.key(1), jnp.ones((2, 16), jnp.int32))["params"]
    unstacked = unstack_scanned_params(sp)
    assert "layers_scan" not in unstacked
    assert f"block_{model.config.num_layers - 1}" in unstacked
    restacked = stack_layer_params(unstacked)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: jnp.array_equal(a, b), sp, restacked)
    )


@pytest.mark.slow
def test_scanned_checkpoint_generates_like_unscanned(lm):
    """VERDICT #4: a scan_layers=True-trained checkpoint must generate, and
    its output must match the unscanned model driven by the same weights."""
    import dataclasses

    import optax

    from pytorch_distributed_training_tpu.models.relayout import (
        unstack_scanned_params,
    )

    model, _ = lm
    scfg = dataclasses.replace(model.config, scan_layers=True)
    scanned = GPT2LMModel(scfg)
    params = scanned.init(jax.random.key(2), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]

    # a couple of real optimizer steps so the weights are "trained", the
    # exact shape a train_lm-default checkpoint restores to
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    batch = jnp.asarray(
        np.random.default_rng(0).integers(0, scfg.vocab_size, (2, 16)),
        jnp.int32,
    )

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = scanned.apply({"params": p}, batch)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], batch[:, 1:]
            ).mean()

        g = jax.grad(loss_fn)(params)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state

    for _ in range(2):
        params, opt_state = step(params, opt_state)

    prompt = np.asarray([[5, 3, 7, 2], [1, 1, 4, 9]], np.int32)
    out_scanned = generate(scanned, params, prompt, max_new_tokens=6)

    unscanned_params = unstack_scanned_params(params)
    out_unscanned = generate(model, unscanned_params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(
        np.asarray(out_scanned), np.asarray(out_unscanned)
    )
    # and against the no-cache reference loop on the unscanned model
    ref = _greedy_no_cache(model, unscanned_params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out_scanned), ref)


def test_generate_cli_smoke(tmp_path):
    """The generation CLI end-to-end on a tiny model with the byte
    tokenizer (random weights; checks the decode+detokenize plumbing), and
    params-only checkpoint restore feeding it."""
    import jax

    from pytorch_distributed_training_tpu.cli.generate_lm import main
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt
    from pytorch_distributed_training_tpu.train.state import TrainState

    text = main([
        "--model", "gpt2-tiny", "--prompt", "hello", "--max-new-tokens", "4",
        "--no-stop-at-eot",
    ])
    assert isinstance(text, str)

    # round-trip: save a train state, restore only params
    import optax

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.utils.config import model_preset

    model = GPT2LMModel(model_preset("gpt2-tiny"))
    import jax.numpy as jnp

    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))["params"]
    tx = optax.sgd(1e-3)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        dropout_rng=jax.random.key(1),
        apply_fn=model.apply,
        tx=tx,
    )
    ckpt.save_checkpoint(str(tmp_path / "ck"), state)
    for like in (None, params):  # full read and true partial restore
        restored = ckpt.restore_params(str(tmp_path / "ck"), params_like=like)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b)
            ),
            params, restored,
        )


def test_generate_cli_prompt_file(tmp_path, capsys):
    """--prompt-file: every line generates and prints its own continuation
    (the old CLI silently dropped all but row 0 of the batch)."""
    from pytorch_distributed_training_tpu.cli.generate_lm import main

    pf = tmp_path / "prompts.txt"
    prompts = ["hello there", "a much longer prompt line", "bye"]
    pf.write_text("\n".join(prompts) + "\n")
    texts = main([
        "--model", "gpt2-tiny", "--prompt-file", str(pf),
        "--max-new-tokens", "4", "--no-stop-at-eot",
    ])
    assert isinstance(texts, list) and len(texts) == 3
    assert all(isinstance(t, str) for t in texts)
    printed = capsys.readouterr().out.splitlines()
    assert len(printed) == 3
    for prompt, text, line in zip(prompts, texts, printed):
        assert line == prompt + text

    # ragged rows behave like solo runs (the positional fix, through the CLI
    # path): re-generate line 2 alone and compare
    solo = main([
        "--model", "gpt2-tiny", "--prompt", prompts[2],
        "--max-new-tokens", "4", "--no-stop-at-eot",
    ])
    assert solo == texts[2]


def test_generate_cli_scanned_checkpoint(tmp_path):
    """A scan_layers=True training checkpoint (the train_lm default) must
    generate through the CLI with zero extra flags: layout is detected from
    checkpoint metadata and re-laid-out inside generate()."""
    import dataclasses

    import optax

    from pytorch_distributed_training_tpu.cli.generate_lm import main
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt
    from pytorch_distributed_training_tpu.train.state import TrainState

    scfg = model_preset("gpt2-tiny", scan_layers=True)
    model = GPT2LMModel(scfg)
    params = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))[
        "params"
    ]
    tx = optax.sgd(1e-3)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        dropout_rng=jax.random.key(1),
        apply_fn=model.apply,
        tx=tx,
    )
    ckpt.save_checkpoint(str(tmp_path / "ck"), state)
    assert ckpt.saved_params_scanned(str(tmp_path / "ck"))

    text = main([
        "--model", "gpt2-tiny", "--prompt", "hello", "--max-new-tokens", "4",
        "--no-stop-at-eot", "--checkpoint-dir", str(tmp_path / "ck"),
    ])
    assert isinstance(text, str)

    # parity: the same weights unstacked through an unscanned model produce
    # the same continuation
    from pytorch_distributed_training_tpu.models.relayout import (
        unstack_scanned_params,
    )

    ucfg = dataclasses.replace(scfg, scan_layers=False)
    prompt = np.asarray([[5, 3, 7, 2]], np.int32)
    out_s = generate(model, params, prompt, max_new_tokens=5)
    out_u = generate(
        GPT2LMModel(ucfg), unstack_scanned_params(params), prompt,
        max_new_tokens=5,
    )
    np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_u))
